"""Multi-node-on-one-machine test harness.

Parity: python/ray/cluster_utils.py:135 ``Cluster`` — the linchpin of the
reference's distributed test strategy (SURVEY.md §4): start a control store
plus N node agents as separate processes on one machine, each with its own
resource spec; kill/restart nodes for fault-tolerance tests.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core.control_store import ControlStore
from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient


def spawn_node_agent(
    control_address: str,
    session_id: str,
    resources: Dict[str, float],
    labels: Optional[Dict[str, str]] = None,
    startup_timeout_s: float = 60.0,
):
    """Start a node agent process and wait for its one-line JSON startup
    handshake. Shared by the test Cluster and the autoscaler's
    LocalNodeProvider — the spawn protocol must not fork."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["RT_CONFIG_SNAPSHOT"] = config.snapshot()
    # stderr goes to a FILE, not a pipe: nothing drains node logs for the
    # process's lifetime, and a filled 64KB pipe would block the agent
    log_dir = os.path.join(config.temp_dir, f"session_{session_id[:8]}", "logs")
    os.makedirs(log_dir, exist_ok=True)
    stderr_path = os.path.join(log_dir, f"node-{uuid.uuid4().hex[:8]}.err")
    stderr_f = open(stderr_path, "wb")
    try:
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.core.node_main",
                "--control-address", control_address,
                "--session-id", session_id,
                "--resources", json.dumps(resources),
                "--labels", json.dumps(labels or {}),
            ],
            env=env, stdout=subprocess.PIPE, stderr=stderr_f,
            start_new_session=True,
        )
    finally:
        stderr_f.close()
    import selectors

    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    try:
        ready = sel.select(timeout=startup_timeout_s)
    finally:
        sel.close()
    line = proc.stdout.readline().decode().strip() if ready else ""
    if not line:
        # EOF (startup crash) or hang: reap and surface the real cause
        try:
            proc.kill()
            proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            pass
        try:
            with open(stderr_path, "rb") as f:
                tail = f.read()[-2000:].decode(errors="replace")
        except OSError:
            tail = ""
        raise RuntimeError(
            f"node agent spawn failed (rc={proc.returncode}): {tail}"
        )
    return proc, json.loads(line)


class ClusterNode:
    def __init__(self, node_id: str, address: str, proc: subprocess.Popen):
        self.node_id = node_id
        self.address = address
        self.proc = proc


class Cluster:
    def __init__(self):
        self.session_id = uuid.uuid4().hex
        self.control = ControlStore(self.session_id)
        self.control.start()
        from ray_tpu.utils.gateway import Gateway

        self.gateway = Gateway(self.control.address)
        self.gateway.start()
        self.nodes: List[ClusterNode] = []

    @property
    def address(self) -> str:
        return self.control.address

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> ClusterNode:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        res["TPU"] = float(num_tpus)
        proc, info = spawn_node_agent(
            self.address, self.session_id, res, labels
        )
        node = ClusterNode(info["node_id"], info["address"], proc)
        self.nodes.append(node)
        if wait:
            self.wait_for_nodes(len(self.nodes))
        return node

    def wait_for_nodes(self, count: Optional[int] = None, timeout_s: float = 30.0) -> None:
        count = count if count is not None else len(self.nodes)
        client = RpcClient(self.address, name="cluster-wait")
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_nodes")
                if len(nodes) >= count:
                    return
                time.sleep(0.05)
            raise TimeoutError(f"cluster did not reach {count} nodes")
        finally:
            client.close()

    def kill_node(self, node: ClusterNode) -> None:
        """Hard-kill a node agent AND its workers for FT tests. Workers
        run in their own sessions (start_new_session), so killing the
        agent's group alone would leave them orphaned and split-brain
        until their agent-watchdog fires — a real machine death takes
        everything down at once, and so must this simulation."""
        worker_pids: List[int] = []
        try:
            out = subprocess.run(
                ["pgrep", "-P", str(node.proc.pid)],
                capture_output=True, text=True, timeout=5,
            ).stdout
            worker_pids = [int(p) for p in out.split()]
        except Exception:  # noqa: BLE001
            pass
        try:
            os.killpg(os.getpgid(node.proc.pid), 9)
        except (ProcessLookupError, PermissionError):
            node.proc.kill()
        for pid in worker_pids:
            try:
                os.killpg(os.getpgid(pid), 9)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(pid, 9)
                except ProcessLookupError:
                    pass
        node.proc.wait()
        client = RpcClient(self.address, name="cluster-kill")
        try:
            client.call("drain_node", node_id=node.node_id)
        except Exception:  # noqa: BLE001
            pass
        finally:
            client.close()
        self.nodes = [n for n in self.nodes if n is not node]

    def list_state(self) -> List[Dict[str, Any]]:
        client = RpcClient(self.address, name="cluster-state")
        try:
            return client.call("get_nodes")
        finally:
            client.close()

    def shutdown(self) -> None:
        try:
            self.gateway.stop()
        except Exception:  # noqa: BLE001
            pass
        for node in list(self.nodes):
            try:
                os.killpg(os.getpgid(node.proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                node.proc.terminate()
        for node in self.nodes:
            try:
                node.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                node.proc.kill()
        self.nodes.clear()
        self.control.stop()
