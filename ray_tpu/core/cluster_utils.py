"""Multi-node-on-one-machine test harness.

Parity: python/ray/cluster_utils.py:135 ``Cluster`` — the linchpin of the
reference's distributed test strategy (SURVEY.md §4): start a control store
plus N node agents as separate processes on one machine, each with its own
resource spec; kill/restart nodes for fault-tolerance tests.
"""

from __future__ import annotations

import atexit
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core.control_store import ControlStore
from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient

# Every daemon spawned through _spawn_with_handshake, for the atexit
# sweep: a test/bench run that dies without Cluster.shutdown() (assertion
# mid-fixture, Ctrl-C) must not leave node_main/head_main process groups
# — and their workers' /dev/shm segments — behind.
_SPAWNED: List[subprocess.Popen] = []
_atexit_registered = False

_DAEMON_MARKERS = (
    "ray_tpu.core.node_main",
    "ray_tpu.core.head_main",
    "ray_tpu.core.worker_main",
)
_SHM_DEBRIS_GLOBS = (
    "/dev/shm/rtshm_*", "/dev/shm/rtpool_*", "/dev/shm/rtchan_*",
    "/tmp/rtspill_*",
)


def _kill_group(pid: int, sig: int = signal.SIGKILL) -> None:
    try:
        os.killpg(os.getpgid(pid), sig)
    except (ProcessLookupError, PermissionError):
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass


def _atexit_sweep() -> None:
    for proc in _SPAWNED:
        if proc.poll() is None:
            _kill_group(proc.pid, signal.SIGTERM)
    deadline = time.monotonic() + 3.0
    for proc in _SPAWNED:
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if proc.poll() is None:
            _kill_group(proc.pid, signal.SIGKILL)


def _track_spawned(proc: subprocess.Popen) -> None:
    global _atexit_registered
    if not _atexit_registered:
        atexit.register(_atexit_sweep)
        _atexit_registered = True
    _SPAWNED.append(proc)
    # completed daemons need no tracking; stop the list growing unbounded
    # in long sessions (autoscaler churn spawns many short-lived agents)
    if len(_SPAWNED) > 256:
        _SPAWNED[:] = [p for p in _SPAWNED if p.poll() is None]


def sweep_stale_runtime(min_debris_age_s: float = 10.0) -> Dict[str, int]:
    """Reap debris a SIGKILLed previous run left behind: orphaned
    ray_tpu daemon processes (node_main/head_main/worker_main whose
    spawning driver is gone — they reparent to pid 1) and their shm/spill
    files (/dev/shm/rtshm_* segments, rtpool_* recycle pools, rtchan_*
    compiled-graph channels, /tmp/rtspill_* spill dirs).

    Call at test-session / bench start: leaked node_main processes hold
    CPU and ports that cascade-fail late test_serve runs and depress
    serve/RPC benches. Concurrent-run safety, in order: only ORPHANS die
    (a daemon whose parent — another live pytest/bench/driver — still
    exists is left alone); files mapped by ANY live process
    (/proc/*/maps scan — mmap writes never touch st_mtime, so age alone
    can't prove staleness) are skipped; files carrying the session
    prefix of a surviving daemon are skipped; and the
    ``min_debris_age_s`` gate protects clusters mid-boot whose files
    exist but are not yet mapped.

    Returns {"killed": n_processes, "removed": n_paths}."""
    killed = 0
    live_sessions: set = set()
    mapped_paths: set = set()
    for pid_dir in glob.glob("/proc/[0-9]*"):
        try:
            pid = int(os.path.basename(pid_dir))
            if pid == os.getpid():
                continue
            with open(os.path.join(pid_dir, "cmdline"), "rb") as f:
                argv = f.read().split(b"\x00")
            cmdline = b" ".join(argv).decode(errors="replace")
            if any(m in cmdline for m in _DAEMON_MARKERS):
                with open(os.path.join(pid_dir, "stat")) as f:
                    # field 4 of /proc/pid/stat is ppid; comm (field 2)
                    # may contain spaces but is parenthesized — split
                    # after ')'
                    ppid = int(f.read().rsplit(")", 1)[1].split()[1])
                if ppid == 1 or not os.path.exists(f"/proc/{ppid}"):
                    _kill_group(pid, signal.SIGKILL)
                    killed += 1
                    continue
                # surviving daemon: remember its session so its files
                # (incl. never-mapped recycle-pool segments) are spared
                args = [a.decode(errors="replace") for a in argv]
                if "--session-id" in args:
                    sid = args[args.index("--session-id") + 1]
                    live_sessions.add(sid[:8])
            # any live process's mappings protect the files it holds
            with open(os.path.join(pid_dir, "maps")) as f:
                for line in f:
                    if "/dev/shm/rt" in line or "/tmp/rtspill_" in line:
                        mapped_paths.add(
                            line.split(None, 5)[-1].strip()
                            .replace(" (deleted)", "")
                        )
        except (OSError, ValueError, IndexError):
            continue  # process vanished mid-scan
    removed = 0
    cutoff = time.time() - min_debris_age_s
    for pattern in _SHM_DEBRIS_GLOBS:
        for path in glob.glob(pattern):
            try:
                name = os.path.basename(path)
                session8 = (
                    name.split("_")[1][:8] if "_" in name else ""
                )
                if path in mapped_paths or session8 in live_sessions:
                    continue  # a live run owns it
                if os.lstat(path).st_mtime >= cutoff:
                    continue
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.unlink(path)
                removed += 1
            except OSError:
                continue
    return {"killed": killed, "removed": removed}


def _spawn_with_handshake(
    cmd: List[str],
    session_id: str,
    log_prefix: str,
    startup_timeout_s: float = 60.0,
):
    """Spawn a cluster daemon and wait for its one-line JSON startup
    handshake — THE spawn protocol, shared by node agents and standalone
    heads (it must not fork per call site)."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["RT_CONFIG_SNAPSHOT"] = config.snapshot()
    # stderr goes to a FILE, not a pipe: nothing drains daemon logs for
    # the process's lifetime, and a filled 64KB pipe would block it
    log_dir = os.path.join(config.temp_dir, f"session_{session_id[:8]}", "logs")
    os.makedirs(log_dir, exist_ok=True)
    stderr_path = os.path.join(
        log_dir, f"{log_prefix}-{uuid.uuid4().hex[:8]}.err"
    )
    stderr_f = open(stderr_path, "wb")
    try:
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=stderr_f,
            start_new_session=True,
        )
    finally:
        stderr_f.close()
    _track_spawned(proc)
    import selectors

    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    try:
        ready = sel.select(timeout=startup_timeout_s)
    finally:
        sel.close()
    line = proc.stdout.readline().decode().strip() if ready else ""
    if not line:
        # EOF (startup crash) or hang: reap and surface the real cause
        try:
            proc.kill()
            proc.wait(timeout=10)
        except Exception:  # noqa: BLE001
            pass
        try:
            with open(stderr_path, "rb") as f:
                tail = f.read()[-2000:].decode(errors="replace")
        except OSError:
            tail = ""
        raise RuntimeError(
            f"{log_prefix} spawn failed (rc={proc.returncode}): {tail}"
        )
    return proc, json.loads(line)


def spawn_node_agent(
    control_address: str,
    session_id: str,
    resources: Dict[str, float],
    labels: Optional[Dict[str, str]] = None,
    startup_timeout_s: float = 60.0,
):
    """Start a node agent process (shared by the test Cluster and the
    autoscaler's LocalNodeProvider)."""
    return _spawn_with_handshake(
        [
            sys.executable, "-m", "ray_tpu.core.node_main",
            "--control-address", control_address,
            "--session-id", session_id,
            "--resources", json.dumps(resources),
            "--labels", json.dumps(labels or {}),
        ],
        session_id, "node", startup_timeout_s,
    )


def spawn_head(
    session_id: str,
    host: str = "127.0.0.1",
    port: int = 0,
    persistence_path: Optional[str] = None,
    address_file: Optional[str] = None,
    startup_timeout_s: float = 60.0,
):
    """Start a standalone head process (core/head_main.py) — the harness
    for head fault-tolerance tests (kill -9 the head, spawn it again on
    the same port + durable log) and for `rt head start`."""
    cmd = [
        sys.executable, "-m", "ray_tpu.core.head_main",
        "--host", host, "--port", str(port),
        "--session-id", session_id,
    ]
    if persistence_path:
        cmd += ["--persist", persistence_path]
    if address_file:
        cmd += ["--address-file", address_file]
    return _spawn_with_handshake(cmd, session_id, "head", startup_timeout_s)


class ClusterNode:
    def __init__(self, node_id: str, address: str, proc: subprocess.Popen):
        self.node_id = node_id
        self.address = address
        self.proc = proc


class Cluster:
    """external_head=True runs the control store as its own process (via
    spawn_head) so tests can kill -9 and restart it; the default keeps
    the store in-process (fast, no failover surface)."""

    def __init__(self, external_head: bool = False,
                 persistence_path: Optional[str] = None,
                 address_file: Optional[str] = None):
        self.session_id = uuid.uuid4().hex
        self.persistence_path = persistence_path
        self.address_file = address_file
        self.control: Optional[ControlStore] = None
        self.head_proc: Optional[subprocess.Popen] = None
        self.gateway = None
        if external_head:
            self.head_proc, info = spawn_head(
                self.session_id,
                persistence_path=persistence_path,
                address_file=address_file,
            )
            self._address = info["address"]
            self._head_host, head_port = self._address.rsplit(":", 1)
            self._head_port = int(head_port)
        else:
            self.control = ControlStore(
                self.session_id, persistence_path=persistence_path
            )
            self.control.start()
            from ray_tpu.utils.gateway import Gateway

            self.gateway = Gateway(self.control.address)
            self.gateway.start()
            self._address = self.control.address
        self.nodes: List[ClusterNode] = []

    @property
    def address(self) -> str:
        return self._address

    # -- head fault-tolerance harness (external_head only) --

    def kill_head(self) -> None:
        """SIGKILL the head process — the failure HA must survive."""
        assert self.head_proc is not None, "kill_head needs external_head"
        try:
            os.killpg(os.getpgid(self.head_proc.pid), 9)
        except (ProcessLookupError, PermissionError):
            self.head_proc.kill()
        self.head_proc.wait()

    def restart_head(self, wait_ready_s: float = 60.0) -> None:
        """Respawn the head on the SAME address + durable log and wait
        until its control store answers."""
        self.head_proc, info = spawn_head(
            self.session_id,
            host=self._head_host,
            port=self._head_port,
            persistence_path=self.persistence_path,
            address_file=self.address_file,
        )
        assert info["address"] == self._address, (
            f"head restarted at {info['address']}, expected {self._address}"
        )
        client = RpcClient(self._address, name="head-wait")
        deadline = time.monotonic() + wait_ready_s
        try:
            while time.monotonic() < deadline:
                try:
                    client.call("ha_status", timeout_s=5.0)
                    return
                except Exception:  # noqa: BLE001 — still booting
                    time.sleep(0.1)
            raise TimeoutError("restarted head did not become ready")
        finally:
            client.close()

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> ClusterNode:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        res["TPU"] = float(num_tpus)
        proc, info = spawn_node_agent(
            self.address, self.session_id, res, labels
        )
        node = ClusterNode(info["node_id"], info["address"], proc)
        self.nodes.append(node)
        if wait:
            self.wait_for_nodes(len(self.nodes))
        return node

    def wait_for_nodes(self, count: Optional[int] = None, timeout_s: float = 30.0) -> None:
        count = count if count is not None else len(self.nodes)
        client = RpcClient(self.address, name="cluster-wait")
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_nodes")
                if len(nodes) >= count:
                    return
                time.sleep(0.05)
            raise TimeoutError(f"cluster did not reach {count} nodes")
        finally:
            client.close()

    def kill_node(self, node: ClusterNode) -> None:
        """Hard-kill a node agent AND its workers for FT tests. Workers
        run in their own sessions (start_new_session), so killing the
        agent's group alone would leave them orphaned and split-brain
        until their agent-watchdog fires — a real machine death takes
        everything down at once, and so must this simulation."""
        worker_pids: List[int] = []
        try:
            out = subprocess.run(
                ["pgrep", "-P", str(node.proc.pid)],
                capture_output=True, text=True, timeout=5,
            ).stdout
            worker_pids = [int(p) for p in out.split()]
        except Exception:  # noqa: BLE001
            pass
        try:
            os.killpg(os.getpgid(node.proc.pid), 9)
        except (ProcessLookupError, PermissionError):
            node.proc.kill()
        for pid in worker_pids:
            try:
                os.killpg(os.getpgid(pid), 9)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(pid, 9)
                except ProcessLookupError:
                    pass
        node.proc.wait()
        client = RpcClient(self.address, name="cluster-kill")
        try:
            client.call("drain_node", node_id=node.node_id)
        except Exception:  # noqa: BLE001
            pass
        finally:
            client.close()
        self.nodes = [n for n in self.nodes if n is not node]

    def list_state(self) -> List[Dict[str, Any]]:
        client = RpcClient(self.address, name="cluster-state")
        try:
            return client.call("get_nodes")
        finally:
            client.close()

    def shutdown(self) -> None:
        if self.gateway is not None:
            try:
                self.gateway.stop()
            except Exception:  # noqa: BLE001
                pass
        for node in list(self.nodes):
            try:
                os.killpg(os.getpgid(node.proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                node.proc.terminate()
        for node in self.nodes:
            try:
                node.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                node.proc.kill()
        self.nodes.clear()
        if self.control is not None:
            self.control.stop()
        if self.head_proc is not None and self.head_proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.head_proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                self.head_proc.terminate()
            try:
                self.head_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.head_proc.kill()
