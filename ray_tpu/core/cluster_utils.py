"""Multi-node-on-one-machine test harness.

Parity: python/ray/cluster_utils.py:135 ``Cluster`` — the linchpin of the
reference's distributed test strategy (SURVEY.md §4): start a control store
plus N node agents as separate processes on one machine, each with its own
resource spec; kill/restart nodes for fault-tolerance tests.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core.control_store import ControlStore
from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient


class ClusterNode:
    def __init__(self, node_id: str, address: str, proc: subprocess.Popen):
        self.node_id = node_id
        self.address = address
        self.proc = proc


class Cluster:
    def __init__(self):
        self.session_id = uuid.uuid4().hex
        self.control = ControlStore(self.session_id)
        self.control.start()
        self.nodes: List[ClusterNode] = []

    @property
    def address(self) -> str:
        return self.control.address

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> ClusterNode:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        res["TPU"] = float(num_tpus)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["RT_CONFIG_SNAPSHOT"] = config.snapshot()
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.core.node_main",
                "--control-address", self.address,
                "--session-id", self.session_id,
                "--resources", json.dumps(res),
                "--labels", json.dumps(labels or {}),
            ],
            env=env, stdout=subprocess.PIPE, stderr=None, start_new_session=True,
        )
        line = proc.stdout.readline().decode().strip()
        info = json.loads(line)
        node = ClusterNode(info["node_id"], info["address"], proc)
        self.nodes.append(node)
        if wait:
            self.wait_for_nodes(len(self.nodes))
        return node

    def wait_for_nodes(self, count: Optional[int] = None, timeout_s: float = 30.0) -> None:
        count = count if count is not None else len(self.nodes)
        client = RpcClient(self.address, name="cluster-wait")
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                nodes = client.call("get_nodes")
                if len(nodes) >= count:
                    return
                time.sleep(0.05)
            raise TimeoutError(f"cluster did not reach {count} nodes")
        finally:
            client.close()

    def kill_node(self, node: ClusterNode) -> None:
        """Hard-kill a node agent (and its workers) for FT tests."""
        try:
            os.killpg(os.getpgid(node.proc.pid), 9)
        except (ProcessLookupError, PermissionError):
            node.proc.kill()
        node.proc.wait()
        client = RpcClient(self.address, name="cluster-kill")
        try:
            client.call("drain_node", node_id=node.node_id)
        except Exception:  # noqa: BLE001
            pass
        finally:
            client.close()
        self.nodes = [n for n in self.nodes if n is not node]

    def list_state(self) -> List[Dict[str, Any]]:
        client = RpcClient(self.address, name="cluster-state")
        try:
            return client.call("get_nodes")
        finally:
            client.close()

    def shutdown(self) -> None:
        for node in list(self.nodes):
            try:
                os.killpg(os.getpgid(node.proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                node.proc.terminate()
        for node in self.nodes:
            try:
                node.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                node.proc.kill()
        self.nodes.clear()
        self.control.stop()
