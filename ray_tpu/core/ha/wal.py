"""Durable control-plane log: snapshot + write-ahead log + compaction.

Parity: the reference's gcs_table_storage over a durable StoreClient
(Redis FT mode). The control store funnels every durable state mutation
through one choke point (``ControlStore._apply``) which appends the
fully-resolved operation here; recovery loads the last snapshot and
replays the WAL tail through the same mutation functions, so the
restored tables are byte-identical to the pre-crash state.

Backends are pluggable behind the small ``load_snapshot / wal_append /
...`` surface; ``FileBackend`` is the built-in local-filesystem one
(on a TPU pod the head's persistent disk or an NFS export — the
TPU-native stand-in for the reference's Redis deployment).

WAL frame: ``[4-byte LE crc32][4-byte LE length][pickled (seq, op,
args)]``. Replay stops at the first corrupt or truncated frame (a torn
tail write from the crash is expected and harmless — that mutation
never acked). The monotonic ``seq`` makes compaction crash-atomic: the
snapshot records the last folded seq, and recovery skips WAL frames at
or below it — a crash between snapshot rename and WAL truncation
cannot double-apply ops.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<II")  # crc32, payload length

SNAPSHOT_VERSION = 1


class SnapshotCorruptError(Exception):
    """The snapshot file exists but cannot be read."""


class FileBackend:
    """Snapshot at ``path``, WAL at ``path + ".wal"``."""

    def __init__(self, path: str):
        self.snapshot_path = path
        self.wal_path = path + ".wal"
        self._wal_f = None

    # -- snapshot --

    def load_snapshot(self) -> Optional[Dict[str, Any]]:
        """Load the snapshot; None means ABSENT. A present-but-unreadable
        snapshot raises SnapshotCorruptError — conflating the two would
        let recovery replay the post-compaction WAL tail onto empty
        tables and present partial state as authoritative."""
        if not os.path.exists(self.snapshot_path):
            return None
        try:
            with open(self.snapshot_path, "rb") as f:
                return pickle.load(f)
        except Exception as e:  # noqa: BLE001
            raise SnapshotCorruptError(
                f"HA snapshot unreadable: {self.snapshot_path}: {e}"
            ) from e

    def quarantine(self) -> None:
        """Set aside the snapshot+WAL pair (suffix .corrupt) so a fresh
        start never destroys the evidence of what it could not read."""
        for path in (self.snapshot_path, self.wal_path):
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    logger.exception("cannot quarantine %s", path)
        if self._wal_f is not None:
            try:
                self._wal_f.close()
            except OSError:
                pass
            self._wal_f = None

    def write_snapshot(self, payload: Dict[str, Any]) -> None:
        os.makedirs(
            os.path.dirname(os.path.abspath(self.snapshot_path)), exist_ok=True
        )
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)

    # -- WAL --

    def wal_iter(self) -> Iterator[Tuple[int, str, tuple]]:
        """Yield (seq, op, args) records; stop silently at a torn/corrupt
        tail."""
        if not os.path.exists(self.wal_path):
            return
        with open(self.wal_path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                crc, length = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    logger.warning(
                        "WAL %s: torn/corrupt tail record, stopping replay",
                        self.wal_path,
                    )
                    return
                try:
                    yield pickle.loads(payload)
                except Exception:  # noqa: BLE001
                    logger.exception("WAL record unpickle failed; stopping")
                    return

    @staticmethod
    def wal_frame(record: Tuple[int, str, tuple]) -> bytes:
        """Serialize one record into its on-disk frame (crc + len + body).
        Framing is identical whether the frame is written alone or as part
        of a group, so group commit changes WAL bytes only in write-call
        granularity, never in content."""
        payload = pickle.dumps(record)
        return _HDR.pack(zlib.crc32(payload), len(payload)) + payload

    def wal_append(self, record: Tuple[int, str, tuple],
                   fsync: bool = False) -> None:
        self._wal_write(self.wal_frame(record), fsync)

    def wal_append_frames(self, frames: List[bytes],
                          fsync: bool = False) -> None:
        """Group commit: land many frames as ONE buffered write (+ at most
        one fsync). Because the group is a single contiguous write of
        whole frames, a crash can only tear the tail — replay recovers a
        clean frame prefix, never a partial mid-group record."""
        self._wal_write(b"".join(frames), fsync)

    def _wal_write(self, data: bytes, fsync: bool) -> None:
        if self._wal_f is None:
            os.makedirs(
                os.path.dirname(os.path.abspath(self.wal_path)), exist_ok=True
            )
            self._wal_f = open(self.wal_path, "ab")
        self._wal_f.write(data)
        self._wal_f.flush()
        if fsync:
            os.fsync(self._wal_f.fileno())

    def wal_reset(self) -> None:
        """Truncate the WAL (called right after a snapshot is durable)."""
        if self._wal_f is not None:
            self._wal_f.close()
            self._wal_f = None
        os.makedirs(
            os.path.dirname(os.path.abspath(self.wal_path)), exist_ok=True
        )
        with open(self.wal_path, "wb"):
            pass

    def close(self) -> None:
        if self._wal_f is not None:
            try:
                self._wal_f.close()
            except OSError:
                pass
            self._wal_f = None


class HAState:
    """Snapshot + WAL lifecycle for one control store.

    The caller (control store) serializes calls: ``append`` runs under the
    store lock, so records are totally ordered and a compaction snapshot
    taken inline is consistent with the log position.

    Group commit (``group_commit_ms > 0``): ``append`` frames the record
    and buffers it instead of writing; one flusher thread lands the
    accumulated frames as a single buffered write (+ one fsync when
    configured) per window. Durability for callers comes from
    ``barrier()`` — the control store invokes it before every RPC reply,
    so the per-op contract "acked implies in the WAL" is unchanged; a
    barrier also cuts the window short, so a lone synchronous writer pays
    one thread handoff, not a full window. Crash atomicity falls out of
    the framing: the group is one contiguous write, so a torn tail is
    always a whole-frame prefix and replay recovers exactly the applied
    prefix.
    """

    def __init__(
        self,
        backend: FileBackend,
        compact_entries: int = 1000,
        fsync: bool = False,
        group_commit_ms: float = 0.0,
    ):
        self.backend = backend
        self.compact_entries = max(1, int(compact_entries))
        self.fsync = fsync
        self.group_commit_ms = max(0.0, float(group_commit_ms))
        self.epoch = 0  # number of recoveries this store's state survived
        self.seq = 0  # last op sequence number handed out
        self.meta: Dict[str, Any] = {}
        self._since_snapshot = 0
        self._appended = 0
        self._compactions = 0
        self._replayed = 0
        # group-commit state, all guarded by _group_cv's lock
        self._group_cv = threading.Condition(threading.Lock())
        self._group_buf: List[bytes] = []
        self._group_top = 0  # highest seq sitting in the buffer
        self._durable_seq = 0  # highest seq flushed (or folded in a snapshot)
        self._group_urgent = False
        self._group_stop = False
        self._group_err: Optional[BaseException] = None
        self._group_thread: Optional[threading.Thread] = None
        self._groups_flushed = 0
        self._tls = threading.local()

    @property
    def group_commit(self) -> bool:
        return self.group_commit_ms > 0

    # -- recovery --

    def recover(self) -> Tuple[Optional[Dict[str, Any]], List[Tuple[str, tuple]]]:
        """Return (snapshot tables or None, WAL tail records). Frames
        whose seq the snapshot already folded in are skipped — they are
        the residue of a crash between snapshot rename and WAL reset.

        A corrupt (present-but-unreadable) snapshot quarantines the
        whole snapshot+WAL pair and starts fresh: replaying the WAL tail
        alone would silently present partial state as authoritative,
        and truncation at start() would destroy the evidence."""
        try:
            snap = self.backend.load_snapshot()
        except SnapshotCorruptError:
            logger.exception(
                "HA snapshot corrupt — quarantining snapshot+WAL "
                "(.corrupt) and starting from empty state"
            )
            self.backend.quarantine()
            self.epoch += 1  # a (degraded) recovery still happened
            return None, []
        tables = None
        snap_seq = 0
        if snap is not None:
            self.epoch = int(snap.get("epoch", 0))
            snap_seq = int(snap.get("seq", 0))
            self.meta = dict(snap.get("meta", {}))
            tables = snap.get("tables")
        self.seq = snap_seq
        records = []
        for rec in self.backend.wal_iter():
            seq, op, args = rec
            if seq <= snap_seq:
                continue  # already folded into the snapshot
            self.seq = max(self.seq, seq)
            records.append((op, args))
        self._replayed = len(records)
        if tables is not None or records:
            self.epoch += 1
        return tables, records

    def start(self, state_fn: Callable[[], Dict[str, Any]],
              meta: Optional[Dict[str, Any]] = None) -> None:
        """Finish recovery: persist a fresh snapshot of the replayed state
        and truncate the WAL, so the next crash replays from here."""
        if meta is not None:
            self.meta.update(meta)
        self._snapshot(state_fn)

    # -- logging --

    def append(self, op: str, args: tuple,
               state_fn: Callable[[], Dict[str, Any]]) -> None:
        """Log one op. Called BEFORE the mutation is applied (an append
        failure must leave memory and log consistent), so the compaction
        check runs first: the snapshot folds only ops that are already
        applied, and the fresh record lands in the reset WAL with
        seq > snapshot seq.

        Compaction is inline, under the caller's store lock: the stall is
        pickle+fsync of the durable tables, every compact_entries ops —
        single-digit ms at this repo's scale envelope. Tune
        RT_HA_WAL_COMPACT_ENTRIES upward if the control plane carries
        MB-scale KV blobs and the periodic pause matters."""
        if self._since_snapshot >= self.compact_entries:
            self._snapshot(state_fn)
            self._compactions += 1
        self.seq += 1
        if self.group_commit:
            frame = self.backend.wal_frame((self.seq, op, args))
            with self._group_cv:
                if self._group_err is not None:
                    # the flusher hit a disk error: earlier buffered ops
                    # may be lost — refuse new appends so nothing acks
                    raise self._group_err
                self._group_buf.append(frame)
                self._group_top = self.seq
                if self._group_thread is None:
                    self._group_thread = threading.Thread(
                        target=self._group_loop, name="wal-group", daemon=True
                    )
                    self._group_thread.start()
                self._group_cv.notify_all()
            self._tls.last_seq = self.seq
        else:
            self.backend.wal_append((self.seq, op, args), fsync=self.fsync)
        self._appended += 1
        self._since_snapshot += 1

    def barrier(self, timeout_s: float = 30.0) -> None:
        """Block until every record THIS thread appended is flushed (and
        fsynced when ``fsync`` is on). The control store calls this from
        the RPC server's post-dispatch hook — i.e. after the handler ran
        but before the reply is sent — so a caller that sees an ack sees
        a durable op, exactly as with per-op appends. A waiting barrier
        marks the group urgent, which makes the flusher skip the rest of
        the window. No-op when group commit is off or this thread has not
        appended anything new."""
        if not self.group_commit:
            return
        last = getattr(self._tls, "last_seq", 0)
        if last <= self._durable_seq:  # lock-free fast path (int read)
            return
        deadline = time.monotonic() + timeout_s
        with self._group_cv:
            while last > self._durable_seq:
                if self._group_err is not None:
                    raise self._group_err
                if self._group_stop:
                    return
                self._group_urgent = True
                self._group_cv.notify_all()
                self._group_cv.wait(0.5)
                if time.monotonic() >= deadline:
                    raise OSError("WAL group-commit flush timed out")

    def _group_loop(self) -> None:
        window = self.group_commit_ms / 1000.0
        with self._group_cv:
            while True:
                while not self._group_buf and not self._group_stop:
                    self._group_cv.wait(1.0)
                if self._group_stop and not self._group_buf:
                    return
                if not self._group_urgent and not self._group_stop:
                    # let a group accumulate; an arriving barrier (urgent)
                    # notifies and cuts this short
                    self._group_cv.wait(window)
                self._flush_group_locked()

    def _flush_group_locked(self) -> None:
        """Write the buffered group. Runs with _group_cv held: appenders
        already serialize on the store lock, and barrier waiters would
        only be waiting on this very write."""
        frames, self._group_buf = self._group_buf, []
        top = self._group_top
        self._group_urgent = False
        if not frames:
            return
        try:
            self.backend.wal_append_frames(frames, fsync=self.fsync)
        except Exception as e:  # noqa: BLE001
            self._group_err = e
            logger.exception(
                "WAL group flush failed — store will refuse further appends"
            )
            self._group_cv.notify_all()
            return
        if top > self._durable_seq:
            self._durable_seq = top
        self._groups_flushed += 1
        self._group_cv.notify_all()

    def _snapshot(self, state_fn: Callable[[], Dict[str, Any]]) -> None:
        if self.group_commit:
            # Hold the group lock across snapshot+reset so the flusher
            # cannot race wal_reset's file-handle swap. Every buffered op
            # is already APPLIED (append precedes its mutation and the
            # store lock serializes _apply), so state_fn() folds the
            # buffer into the snapshot; discard it only AFTER the
            # snapshot is durably renamed, then everything up to seq is
            # durable and waiting barriers can be released.
            with self._group_cv:
                self._write_snapshot_locked(state_fn)
                self._group_buf = []
                self._group_urgent = False
                if self.seq > self._durable_seq:
                    self._durable_seq = self.seq
                self._group_cv.notify_all()
        else:
            self._write_snapshot_locked(state_fn)
        self._since_snapshot = 0

    def _write_snapshot_locked(
        self, state_fn: Callable[[], Dict[str, Any]]
    ) -> None:
        self.backend.write_snapshot({
            "version": SNAPSHOT_VERSION,
            "epoch": self.epoch,
            "seq": self.seq,
            "meta": dict(self.meta),
            "tables": state_fn(),
        })
        self.backend.wal_reset()

    def close(self, state_fn: Optional[Callable[[], Dict[str, Any]]] = None) -> None:
        if state_fn is not None:
            try:
                self._snapshot(state_fn)
            except OSError:
                logger.exception("final HA snapshot failed")
        if self.group_commit:
            with self._group_cv:
                self._group_stop = True
                self._group_cv.notify_all()
            t = self._group_thread
            if t is not None:
                t.join(timeout=5.0)
        self.backend.close()

    def stats(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "wal_appended": self._appended,
            "wal_since_snapshot": self._since_snapshot,
            "wal_replayed": self._replayed,
            "compactions": self._compactions,
            "wal_group_commit_ms": self.group_commit_ms,
            "wal_groups_flushed": self._groups_flushed,
            "wal_durable_seq": self._durable_seq,
            "snapshot_path": self.backend.snapshot_path,
        }
