"""Durable control-plane log: snapshot + write-ahead log + compaction.

Parity: the reference's gcs_table_storage over a durable StoreClient
(Redis FT mode). The control store funnels every durable state mutation
through one choke point (``ControlStore._apply``) which appends the
fully-resolved operation here; recovery loads the last snapshot and
replays the WAL tail through the same mutation functions, so the
restored tables are byte-identical to the pre-crash state.

Backends are pluggable behind the small ``load_snapshot / wal_append /
...`` surface; ``FileBackend`` is the built-in local-filesystem one
(on a TPU pod the head's persistent disk or an NFS export — the
TPU-native stand-in for the reference's Redis deployment).

WAL frame: ``[4-byte LE crc32][4-byte LE length][pickled (seq, op,
args)]``. Replay stops at the first corrupt or truncated frame (a torn
tail write from the crash is expected and harmless — that mutation
never acked). The monotonic ``seq`` makes compaction crash-atomic: the
snapshot records the last folded seq, and recovery skips WAL frames at
or below it — a crash between snapshot rename and WAL truncation
cannot double-apply ops.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<II")  # crc32, payload length

SNAPSHOT_VERSION = 1


class SnapshotCorruptError(Exception):
    """The snapshot file exists but cannot be read."""


class FileBackend:
    """Snapshot at ``path``, WAL at ``path + ".wal"``."""

    def __init__(self, path: str):
        self.snapshot_path = path
        self.wal_path = path + ".wal"
        self._wal_f = None

    # -- snapshot --

    def load_snapshot(self) -> Optional[Dict[str, Any]]:
        """Load the snapshot; None means ABSENT. A present-but-unreadable
        snapshot raises SnapshotCorruptError — conflating the two would
        let recovery replay the post-compaction WAL tail onto empty
        tables and present partial state as authoritative."""
        if not os.path.exists(self.snapshot_path):
            return None
        try:
            with open(self.snapshot_path, "rb") as f:
                return pickle.load(f)
        except Exception as e:  # noqa: BLE001
            raise SnapshotCorruptError(
                f"HA snapshot unreadable: {self.snapshot_path}: {e}"
            ) from e

    def quarantine(self) -> None:
        """Set aside the snapshot+WAL pair (suffix .corrupt) so a fresh
        start never destroys the evidence of what it could not read."""
        for path in (self.snapshot_path, self.wal_path):
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    logger.exception("cannot quarantine %s", path)
        if self._wal_f is not None:
            try:
                self._wal_f.close()
            except OSError:
                pass
            self._wal_f = None

    def write_snapshot(self, payload: Dict[str, Any]) -> None:
        os.makedirs(
            os.path.dirname(os.path.abspath(self.snapshot_path)), exist_ok=True
        )
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)

    # -- WAL --

    def wal_iter(self) -> Iterator[Tuple[int, str, tuple]]:
        """Yield (seq, op, args) records; stop silently at a torn/corrupt
        tail."""
        if not os.path.exists(self.wal_path):
            return
        with open(self.wal_path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                crc, length = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    logger.warning(
                        "WAL %s: torn/corrupt tail record, stopping replay",
                        self.wal_path,
                    )
                    return
                try:
                    yield pickle.loads(payload)
                except Exception:  # noqa: BLE001
                    logger.exception("WAL record unpickle failed; stopping")
                    return

    def wal_append(self, record: Tuple[int, str, tuple],
                   fsync: bool = False) -> None:
        if self._wal_f is None:
            os.makedirs(
                os.path.dirname(os.path.abspath(self.wal_path)), exist_ok=True
            )
            self._wal_f = open(self.wal_path, "ab")
        payload = pickle.dumps(record)
        self._wal_f.write(_HDR.pack(zlib.crc32(payload), len(payload)) + payload)
        self._wal_f.flush()
        if fsync:
            os.fsync(self._wal_f.fileno())

    def wal_reset(self) -> None:
        """Truncate the WAL (called right after a snapshot is durable)."""
        if self._wal_f is not None:
            self._wal_f.close()
            self._wal_f = None
        os.makedirs(
            os.path.dirname(os.path.abspath(self.wal_path)), exist_ok=True
        )
        with open(self.wal_path, "wb"):
            pass

    def close(self) -> None:
        if self._wal_f is not None:
            try:
                self._wal_f.close()
            except OSError:
                pass
            self._wal_f = None


class HAState:
    """Snapshot + WAL lifecycle for one control store.

    The caller (control store) serializes calls: ``append`` runs under the
    store lock, so records are totally ordered and a compaction snapshot
    taken inline is consistent with the log position.
    """

    def __init__(
        self,
        backend: FileBackend,
        compact_entries: int = 1000,
        fsync: bool = False,
    ):
        self.backend = backend
        self.compact_entries = max(1, int(compact_entries))
        self.fsync = fsync
        self.epoch = 0  # number of recoveries this store's state survived
        self.seq = 0  # last op sequence number handed out
        self.meta: Dict[str, Any] = {}
        self._since_snapshot = 0
        self._appended = 0
        self._compactions = 0
        self._replayed = 0

    # -- recovery --

    def recover(self) -> Tuple[Optional[Dict[str, Any]], List[Tuple[str, tuple]]]:
        """Return (snapshot tables or None, WAL tail records). Frames
        whose seq the snapshot already folded in are skipped — they are
        the residue of a crash between snapshot rename and WAL reset.

        A corrupt (present-but-unreadable) snapshot quarantines the
        whole snapshot+WAL pair and starts fresh: replaying the WAL tail
        alone would silently present partial state as authoritative,
        and truncation at start() would destroy the evidence."""
        try:
            snap = self.backend.load_snapshot()
        except SnapshotCorruptError:
            logger.exception(
                "HA snapshot corrupt — quarantining snapshot+WAL "
                "(.corrupt) and starting from empty state"
            )
            self.backend.quarantine()
            self.epoch += 1  # a (degraded) recovery still happened
            return None, []
        tables = None
        snap_seq = 0
        if snap is not None:
            self.epoch = int(snap.get("epoch", 0))
            snap_seq = int(snap.get("seq", 0))
            self.meta = dict(snap.get("meta", {}))
            tables = snap.get("tables")
        self.seq = snap_seq
        records = []
        for rec in self.backend.wal_iter():
            seq, op, args = rec
            if seq <= snap_seq:
                continue  # already folded into the snapshot
            self.seq = max(self.seq, seq)
            records.append((op, args))
        self._replayed = len(records)
        if tables is not None or records:
            self.epoch += 1
        return tables, records

    def start(self, state_fn: Callable[[], Dict[str, Any]],
              meta: Optional[Dict[str, Any]] = None) -> None:
        """Finish recovery: persist a fresh snapshot of the replayed state
        and truncate the WAL, so the next crash replays from here."""
        if meta is not None:
            self.meta.update(meta)
        self._snapshot(state_fn)

    # -- logging --

    def append(self, op: str, args: tuple,
               state_fn: Callable[[], Dict[str, Any]]) -> None:
        """Log one op. Called BEFORE the mutation is applied (an append
        failure must leave memory and log consistent), so the compaction
        check runs first: the snapshot folds only ops that are already
        applied, and the fresh record lands in the reset WAL with
        seq > snapshot seq.

        Compaction is inline, under the caller's store lock: the stall is
        pickle+fsync of the durable tables, every compact_entries ops —
        single-digit ms at this repo's scale envelope. Tune
        RT_HA_WAL_COMPACT_ENTRIES upward if the control plane carries
        MB-scale KV blobs and the periodic pause matters."""
        if self._since_snapshot >= self.compact_entries:
            self._snapshot(state_fn)
            self._compactions += 1
        self.seq += 1
        self.backend.wal_append((self.seq, op, args), fsync=self.fsync)
        self._appended += 1
        self._since_snapshot += 1

    def _snapshot(self, state_fn: Callable[[], Dict[str, Any]]) -> None:
        self.backend.write_snapshot({
            "version": SNAPSHOT_VERSION,
            "epoch": self.epoch,
            "seq": self.seq,
            "meta": dict(self.meta),
            "tables": state_fn(),
        })
        self.backend.wal_reset()
        self._since_snapshot = 0

    def close(self, state_fn: Optional[Callable[[], Dict[str, Any]]] = None) -> None:
        if state_fn is not None:
            try:
                self._snapshot(state_fn)
            except OSError:
                logger.exception("final HA snapshot failed")
        self.backend.close()

    def stats(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "wal_appended": self._appended,
            "wal_since_snapshot": self._since_snapshot,
            "wal_replayed": self._replayed,
            "compactions": self._compactions,
            "snapshot_path": self.backend.snapshot_path,
        }
