"""Head fault tolerance (HA) subsystem.

Parity: the reference's Redis-backed GCS fault tolerance
(gcs_server FT mode: gcs_table_storage over a durable store_client,
full state rebuild on restart, raylet reconnect). Here the durable
store is a write-ahead log + periodic snapshot on the local
filesystem (``wal.py``), the control store replays it through its
mutation choke point, and the cluster re-attaches through the
heartbeat/reattach protocol (``control_store.py`` /
``node_agent.py``) plus the head-address resolver (``reattach.py``).
"""

from ray_tpu.core.ha.reattach import head_resolver, write_head_address  # noqa: F401
from ray_tpu.core.ha.wal import FileBackend, HAState  # noqa: F401
