"""Head address discovery for cluster re-attach.

When the head restarts at a NEW address, live agents/workers/drivers
need a rendezvous to find it. The control store publishes its address to
``config.ha_head_address_file`` (a path on storage the cluster shares —
on a TPU pod, the NFS/persistent-disk mount the head already uses for
its WAL); RPC clients built with :func:`head_resolver` re-read it on
every reconnect attempt. With the flag unset (the default), clients
simply re-dial the address they already know — the same-address restart
case needs no rendezvous at all.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

from ray_tpu.utils.config import config

logger = logging.getLogger(__name__)


def write_head_address(address: str) -> None:
    """Atomically publish the head's current address (no-op when the
    address-file flag is unset)."""
    path = str(config.ha_head_address_file)
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(address)
        os.replace(tmp, path)
    except OSError:
        logger.exception("cannot publish head address to %s", path)


def read_head_address() -> Optional[str]:
    path = str(config.ha_head_address_file)
    if not path:
        return None
    try:
        with open(path) as f:
            addr = f.read().strip()
        return addr or None
    except OSError:
        return None


def head_resolver() -> Callable[[], Optional[str]]:
    """Resolver for RpcClients pointed at the control store: returns the
    currently-published head address, or None to keep the known one."""
    return read_head_address
