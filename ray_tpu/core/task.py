"""Task specs and the @remote function wrapper.

Parity: TaskSpecification (reference src/ray/common/task/task_spec.h) and
RemoteFunction (python/ray/remote_function.py:314 ``_remote``). Functions
are registered once in the control-store KV function table (the reference
stores them in GCS KV; _raylet.pyx task execution fetches by id) and
referenced by content hash in specs.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.utils import serialization


@dataclass
class TaskOptions:
    num_returns: int = 1
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    # None → config.task_max_retries at submit time (system failures only,
    # like the reference's default of 3; app exceptions need
    # retry_exceptions=True).
    max_retries: Optional[int] = None
    retry_exceptions: bool = False
    scheduling_strategy: Any = None  # see core.scheduling docstring
    name: Optional[str] = None
    runtime_env: Optional[Dict[str, Any]] = None
    tensor_transport: str = "object"  # "device" → TPU-RDT returns

    def resource_demand(self, default_cpus: float = 1.0) -> Dict[str, float]:
        demand = dict(self.resources)
        cpus = self.num_cpus if self.num_cpus is not None else default_cpus
        if cpus:
            demand["CPU"] = float(cpus)
        if self.num_tpus:
            demand["TPU"] = float(self.num_tpus)
        return demand


def _merge_options(base: TaskOptions, **overrides) -> TaskOptions:
    merged = TaskOptions(**{**base.__dict__})
    for k, v in overrides.items():
        if v is None and k not in ("scheduling_strategy",):
            continue
        if k == "num_gpus":  # accept the Ray-ism, map onto TPU chips
            k = "num_tpus"
        if k == "num_returns" and v == "streaming":
            v = -1  # wire sentinel for dynamic return count
        if k == "tensor_transport":
            from ray_tpu.core.device_objects import validate_transport

            validate_transport(v)
        if not hasattr(merged, k):
            raise TypeError(f"unknown option {k!r}")
        setattr(merged, k, v)
    return merged


class RemoteFunction:
    """Created by @ray_tpu.remote on a function."""

    def __init__(self, fn, options: TaskOptions):
        self._fn = fn
        self._options = options
        self._blob: Optional[bytes] = None
        self._fn_id: Optional[str] = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _function_blob(self) -> tuple:
        if self._blob is None:
            blob = serialization.dumps_function(self._fn)
            fn_id = hashlib.sha1(blob).hexdigest()[:24]
            self._blob, self._fn_id = blob, fn_id
        return self._fn_id, self._blob

    def options(self, **kwargs) -> "RemoteFunction":
        clone = RemoteFunction(self._fn, _merge_options(self._options, **kwargs))
        clone._blob, clone._fn_id = self._blob, self._fn_id
        return clone

    def remote(self, *args, **kwargs):
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        fn_id, blob = self._function_blob()
        w.register_function(fn_id, blob, self.__name__)
        refs = w.submit_task(
            fn_id=fn_id,
            fn_name=self.__name__,
            args=args,
            kwargs=kwargs,
            options=self._options,
        )
        if self._options.num_returns in (1, -1):
            return refs[0]  # single ref, or the ObjectRefGenerator
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()."
        )


@dataclass
class TaskSpec:
    """The wire form of one task invocation."""

    task_id: Any  # TaskID
    fn_id: str
    fn_name: str
    # packed (args, kwargs) as a serialization.Frame (rides RPC as a raw
    # trailing wire segment) — ObjectRefs travel as refs
    args_frame: Any
    num_returns: int
    owner_address: str
    resources: Dict[str, float]
    max_retries: int = 0
    retry_exceptions: bool = False
    name: Optional[str] = None
    # normalized runtime env (core/runtime_env.py prepare() output)
    runtime_env: Optional[Dict[str, Any]] = None
    # actor fields
    actor_id: Optional[str] = None
    method_name: Optional[str] = None
    # "object" (default) or "device": device-resident returns (TPU-RDT,
    # core/device_objects.py) — jax.Array leaves stay in the executor's
    # HBM; only metadata travels in the reply.
    tensor_transport: str = "object"
