"""Standalone head entrypoint — control store + gateway as their own
process, restartable without taking the cluster down.

Parity: the gcs_server binary (src/ray/gcs/gcs_server_main.cc) in its
FT deployment mode: run the head under a supervisor with a durable log
(--persist), and a crash/restart is a blip — the store rebuilds from
snapshot+WAL (core/ha/), live node agents re-attach during the
reconciliation window, and drivers/workers ride it out via retrying
RPC clients.

`rt head start` wraps this module; `rt head-restart` sends the
``head_restart`` RPC registered here, which re-execs the process with
the same argv (same port, same durable log) — a real process bounce,
used both for ops drills and the failover tests.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import time
import uuid


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="head_main")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, default=0,
        help="control-store port (fix it for restart-in-place)",
    )
    parser.add_argument("--session-id", default=None)
    parser.add_argument(
        "--persist", default=None,
        help="durable-log base path (snapshot at PATH, WAL at PATH.wal)",
    )
    parser.add_argument(
        "--address-file", default=None,
        help="publish the head address here (cluster re-attach rendezvous)",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[head {os.getpid()}] %(levelname)s %(name)s: %(message)s",
    )

    from ray_tpu.utils.config import config

    snapshot = os.environ.get("RT_CONFIG_SNAPSHOT")  # rtlint: ignore[config-hygiene] boot protocol: the snapshot must be read raw BEFORE config is populated from it
    if snapshot:
        config.load_snapshot(snapshot)
    if args.address_file:
        config.set("ha_head_address_file", args.address_file)

    # Crash flight recorder before the control store boots: a head
    # segfault mid-WAL-replay must leave a traceback.
    from ray_tpu.observability import forensics

    forensics.install("head")

    from ray_tpu.core.control_store import ControlStore
    from ray_tpu.utils.gateway import Gateway

    session_id = args.session_id or uuid.uuid4().hex
    control = ControlStore(
        session_id, host=args.host, port=args.port,
        persistence_path=args.persist,
    )
    control.start()
    gateway = Gateway(control.address)
    gateway.start()

    state = {"stop": False, "restart": False}

    def rpc_head_restart(conn):
        """Controlled head bounce: final snapshot, then re-exec with the
        same argv — same port, same durable log, fresh process."""
        if not args.persist:
            raise RuntimeError(
                "head-restart requires a durable log (--persist)"
            )
        if args.port == 0:
            raise RuntimeError(
                "head-restart requires a fixed --port (an ephemeral port "
                "would strand re-attaching clients)"
            )
        state["restart"] = True
        return True

    control._server.register("head_restart", rpc_head_restart)

    print(
        json.dumps({
            "address": control.address,
            "gateway_address": gateway.address,
            "session_id": control.session_id,
            "pid": os.getpid(),
        }),
        flush=True,
    )

    def handle(*_):
        state["stop"] = True

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)
    while not (state["stop"] or state["restart"]):
        time.sleep(0.2)
    restart = state["restart"]
    if restart:
        # let the head_restart reply flush before tearing the server down
        time.sleep(0.2)
    try:
        gateway.stop()
    except Exception:  # noqa: BLE001 — teardown path
        pass
    control.stop()
    if restart:
        logging.getLogger(__name__).info("re-exec for head restart")
        reexec = [
            "--host", args.host, "--port", str(args.port),
            "--session-id", control.session_id,
        ]
        if args.persist:
            reexec += ["--persist", args.persist]
        if args.address_file:
            reexec += ["--address-file", args.address_file]
        os.execv(sys.executable, [sys.executable, "-m",
                                  "ray_tpu.core.head_main", *reexec])


if __name__ == "__main__":
    main()
