"""Mutable shared-memory channels — the compiled-graph data plane.

Parity: the reference's experimental mutable-object channels
(python/ray/experimental/channel/shared_memory_channel.py over
src/ray/core_worker/experimental_mutable_object_manager.cc): a
pre-allocated shm segment REUSED for every message, so a static actor
loop (e.g. pipeline microbatches between co-located stages) pays one
mmap once and then a memcpy + seqlock flip per message instead of
object-store create/seal/get RPCs.

Single-writer single-reader, same host. Layout:
  [seq u64][ack u64][len u64][payload ...]
The writer bumps seq AFTER the payload is fully written; the reader
waits for seq to advance past what it last consumed, copies the payload
out, then publishes ack=seq. The writer BLOCKS until ack catches up
before overwriting — flow control, so a compiled DAG (ray_tpu/dag.py)
can run producers ahead of consumers without losing messages (the
reference's mutable objects block the writer on reader acquisition the
same way).

Waiting is hybrid: a short busy-spin on the shm header (single-digit µs
wakeups when reader and writer run on different cores — the reference's
compiled-graph regime), then a blocking poll on a FIFO doorbell so a
core-starved box (or an idle DAG) parks in the kernel instead of
burning the core the peer needs. The doorbell is only a hint; the shm
header is the ground truth.
"""

from __future__ import annotations

import mmap
import os
import select
import struct
import time
import uuid
from typing import Optional

_HDR = struct.Struct("<QQQ")  # seq, ack, payload_len
_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"

def _spin_window_s() -> float:
    """How long to busy-poll the header before parking on the doorbell.
    On a single-core box spinning only steals the cycles the peer needs
    to produce the message — go straight to the kernel wait."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return 0.0001 if cores > 1 else 0.0


_SPIN_S = _spin_window_s()


class ShmChannel:
    def __init__(self, path: str, capacity: int, create: bool = False):
        self.path = path
        self.capacity = capacity
        # Native core (C++ seqlock + futex handoff, native/src/
        # channel_core.cpp): same shm layout, so native and Python peers
        # interoperate; Python below is the fallback tier.
        self._native = None
        self._nbuf = None
        from ray_tpu import native as native_mod

        lib = native_mod.channel_lib()
        if lib is not None:
            import ctypes

            handle = ctypes.c_void_p()
            rc = lib.rt_chan_open(
                path.encode(), capacity, 1 if create else 0,
                ctypes.byref(handle),
            )
            if rc == 0:
                self._native = (lib, handle)
                self._nbuf = ctypes.create_string_buffer(capacity)
                return
            raise OSError(-rc, f"rt_chan_open({path!r}) failed")
        total = _HDR.size + capacity
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        if create:
            self._mm[: _HDR.size] = _HDR.pack(0, 0, 0)
            for suffix in (".d", ".a"):
                try:
                    os.mkfifo(path + suffix, 0o600)
                except FileExistsError:
                    pass
        # O_RDWR so neither side blocks in open() waiting for a peer
        self._dbell = os.open(path + ".d", os.O_RDWR | os.O_NONBLOCK)
        self._abell = os.open(path + ".a", os.O_RDWR | os.O_NONBLOCK)
        # a reader resumes from what has been CONSUMED (ack), not from the
        # latest seq — a message written before the reader attached (e.g.
        # dag.execute racing the exec loop's channel attach) must still be
        # delivered
        self._last_read = int.from_bytes(self._mm[8:16], "little")

    @classmethod
    def create(cls, capacity: int = 4 * 1024 * 1024) -> "ShmChannel":
        path = os.path.join(_SHM_DIR, f"rtchan_{uuid.uuid4().hex[:16]}")
        return cls(path, capacity, create=True)

    @classmethod
    def attach(cls, path: str, capacity: int) -> "ShmChannel":
        return cls(path, capacity, create=False)

    def handle(self):
        """Picklable (path, capacity) to hand to the peer actor."""
        return {"path": self.path, "capacity": self.capacity}

    @classmethod
    def from_handle(cls, handle) -> "ShmChannel":
        return cls.attach(handle["path"], handle["capacity"])

    def _u64(self, off: int) -> int:
        return int.from_bytes(self._mm[off: off + 8], "little")

    @staticmethod
    def _ring(fd: int) -> None:
        try:
            os.write(fd, b"\x01")
        except BlockingIOError:
            pass  # fifo full: peer has plenty of pending wakeups already

    @staticmethod
    def _drain(fd: int) -> None:
        try:
            os.read(fd, 64)
        except BlockingIOError:
            pass

    def _await(self, ready, bell_fd: int,
               deadline: Optional[float], what: str) -> None:
        """Hybrid wait for ``ready()``: spin on the shm header, then park
        on the doorbell fifo."""
        spin_until = time.monotonic() + _SPIN_S if _SPIN_S else 0.0
        while not ready():
            if _SPIN_S and time.monotonic() < spin_until:
                continue
            remaining = 0.05
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError(f"channel {self.path}: {what}")
            r, _, _ = select.select([bell_fd], [], [], max(remaining, 0.0))
            if r:
                self._drain(bell_fd)

    # -- writer --------------------------------------------------------

    def write(self, payload: bytes, timeout_s: Optional[float] = 60.0) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload {len(payload)} > channel capacity {self.capacity}"
            )
        if self._native is not None:
            lib, handle = self._native
            rc = lib.rt_chan_write(
                handle, payload, len(payload),
                -1.0 if timeout_s is None else float(timeout_s),
            )
            if rc == -1:
                raise TimeoutError(
                    f"channel {self.path}: reader never consumed the "
                    "previous message"
                )
            if rc != 0:
                raise ValueError(f"channel {self.path}: write error {rc}")
            return
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        seq = self._u64(0)
        # flow control: previous message must have been consumed
        self._await(
            lambda: self._u64(8) >= seq, self._abell, deadline,
            f"reader never consumed seq {seq}",
        )
        self._mm[_HDR.size: _HDR.size + len(payload)] = payload
        self._mm[16:24] = len(payload).to_bytes(8, "little")
        # publish: bump seq last (release on x86/ARM via GIL + mmap)
        self._mm[0:8] = (seq + 1).to_bytes(8, "little")
        self._ring(self._dbell)

    # -- reader --------------------------------------------------------

    def read(self, timeout_s: Optional[float] = 30.0) -> bytes:
        """Block until a message newer than the last one read arrives."""
        if self._native is not None:
            lib, handle = self._native
            n = lib.rt_chan_read(
                handle, self._nbuf, self.capacity,
                -1.0 if timeout_s is None else float(timeout_s),
            )
            if n == -1:
                raise TimeoutError(f"channel {self.path}: no message")
            if n < 0:
                raise ValueError(f"channel {self.path}: read error {n}")
            import ctypes

            # string_at copies exactly n bytes (.raw would copy the whole
            # capacity-sized buffer per read — catastrophic at 4 MiB)
            return ctypes.string_at(self._nbuf, int(n))
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        self._await(
            lambda: self._u64(0) > self._last_read, self._dbell, deadline,
            "no message",
        )
        seq = self._u64(0)
        length = self._u64(16)
        data = bytes(self._mm[_HDR.size: _HDR.size + length])
        self._last_read = seq
        self._mm[8:16] = seq.to_bytes(8, "little")  # ack
        self._ring(self._abell)
        return data

    def close(self, unlink: bool = False) -> None:
        if self._native is not None:
            lib, handle = self._native
            self._native = None
            lib.rt_chan_close(handle)
        else:
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass
            for fd in (self._dbell, self._abell):
                try:
                    os.close(fd)
                except OSError:
                    pass
        if unlink:
            for p in (self.path, self.path + ".d", self.path + ".a"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
