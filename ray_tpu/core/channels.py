"""Mutable shared-memory channels — the compiled-graph data plane.

Parity: the reference's experimental mutable-object channels
(python/ray/experimental/channel/shared_memory_channel.py over
src/ray/core_worker/experimental_mutable_object_manager.cc): a
pre-allocated shm segment REUSED for every message, so a static actor
loop (e.g. pipeline microbatches between co-located stages) pays one
mmap once and then a memcpy + seqlock flip per message instead of
object-store create/seal/get RPCs.

Single-writer single-reader, same host. Layout:
  [seq u64][len u64][payload ...]
The writer bumps seq AFTER the payload is fully written; the reader
spins (with backoff) until seq advances past what it last consumed,
then copies the payload out before validating seq is unchanged
(torn-read guard).
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Optional

_HDR = struct.Struct("<QQ")  # seq, payload_len
_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


class ShmChannel:
    def __init__(self, path: str, capacity: int, create: bool = False):
        self.path = path
        self.capacity = capacity
        total = _HDR.size + capacity
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        if create:
            self._mm[: _HDR.size] = _HDR.pack(0, 0)
        self._last_read = int.from_bytes(self._mm[0:8], "little")

    @classmethod
    def create(cls, capacity: int = 4 * 1024 * 1024) -> "ShmChannel":
        path = os.path.join(_SHM_DIR, f"rtchan_{uuid.uuid4().hex[:16]}")
        return cls(path, capacity, create=True)

    @classmethod
    def attach(cls, path: str, capacity: int) -> "ShmChannel":
        return cls(path, capacity, create=False)

    def handle(self):
        """Picklable (path, capacity) to hand to the peer actor."""
        return {"path": self.path, "capacity": self.capacity}

    @classmethod
    def from_handle(cls, handle) -> "ShmChannel":
        return cls.attach(handle["path"], handle["capacity"])

    # -- writer --------------------------------------------------------

    def write(self, payload: bytes) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload {len(payload)} > channel capacity {self.capacity}"
            )
        seq = int.from_bytes(self._mm[0:8], "little")
        self._mm[_HDR.size: _HDR.size + len(payload)] = payload
        self._mm[8:16] = len(payload).to_bytes(8, "little")
        # publish: bump seq last (release on x86/ARM via GIL + mmap)
        self._mm[0:8] = (seq + 1).to_bytes(8, "little")

    # -- reader --------------------------------------------------------

    def read(self, timeout_s: Optional[float] = 30.0) -> bytes:
        """Block until a message newer than the last one read arrives."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        spins = 0
        while True:
            seq = int.from_bytes(self._mm[0:8], "little")
            if seq > self._last_read:
                length = int.from_bytes(self._mm[8:16], "little")
                data = bytes(self._mm[_HDR.size: _HDR.size + length])
                seq2 = int.from_bytes(self._mm[0:8], "little")
                if seq2 == seq:
                    self._last_read = seq
                    return data
                # torn read (writer overwrote mid-copy): retry
                continue
            spins += 1
            if spins > 1000:
                time.sleep(0.0005)
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.path}: no message")

    def close(self, unlink: bool = False) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass
