"""Mutable shared-memory channels — the compiled-graph data plane.

Parity: the reference's experimental mutable-object channels
(python/ray/experimental/channel/shared_memory_channel.py over
src/ray/core_worker/experimental_mutable_object_manager.cc): a
pre-allocated shm segment REUSED for every message, so a static actor
loop (e.g. pipeline microbatches between co-located stages) pays one
mmap once and then a memcpy + seqlock flip per message instead of
object-store create/seal/get RPCs.

Single-writer single-reader. Ring layout (v2 — ``slots`` messages can
be in flight so a compiled pipeline streams rounds without a
per-message rendezvous):

  [seq u64][ack u64][nslots u64][slot_cap u64]        32-byte header
  slot i at 32 + i*(8+slot_cap): [len u64][payload]

``seq`` counts messages PUBLISHED, ``ack`` messages CONSUMED; message k
lives in slot k % nslots. The writer bumps seq AFTER the payload is
fully written and BLOCKS while seq - ack == nslots (ring full); the
reader consumes strictly in order and publishes ack after copying out —
flow control, so a compiled DAG (ray_tpu/dag.py) can run producers up
to ``slots`` rounds ahead of consumers without losing messages.
``slots=1`` reproduces the original one-in-flight seqlock semantics.

Values travel via :meth:`write_value` / :meth:`read_value`:
pickle-5 serialize yields (meta, out-of-band buffer views) and the
views are scatter-gather-copied STRAIGHT into the shm slot — exactly
one host copy per message, never an intermediate join
(tools/check_inband_payloads.py pins the call sites).

Waiting is hybrid: a short busy-spin on the shm header (single-digit µs
wakeups when reader and writer run on different cores — the reference's
compiled-graph regime), then a blocking poll on a FIFO doorbell so a
core-starved box (or an idle DAG) parks in the kernel instead of
burning the core the peer needs. The doorbell is only a hint; the shm
header is the ground truth. The native core (native/src/
channel_core.cpp) shares the layout — native and Python peers
interoperate, and Python rides its begin/commit entry points so even
the fallback-free path publishes through futex-waking atomics.

Cross-host tier: :class:`RpcChannel` — same write/read surface, but
messages ride one worker↔worker ``chan_push`` RPC each, with payloads
≥ 32 KiB wrapped in ``serialization.maybe_frame`` so they travel as
raw out-of-band multiseg segments (utils/rpc.py), never re-pickled
in-band. Flow control is a bounded receiver mailbox (``slots`` deep):
a full mailbox bounces the push and the writer retries until its
deadline. A compiled pipeline places ShmChannel on same-host stage
edges and RpcChannel on cross-host ones (parallel/pipeline.py).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import select
import struct
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.observability import core_metrics
from ray_tpu.utils import serialization

_HDR = struct.Struct("<QQQQ")  # seq, ack, nslots, slot_cap
_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"

def _spin_window_s() -> float:
    """How long to busy-poll the header before parking on the doorbell.
    On a single-core box spinning only steals the cycles the peer needs
    to produce the message — go straight to the kernel wait."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return 0.0001 if cores > 1 else 0.0


_SPIN_S = _spin_window_s()

# Below this total, a native write joins its parts and ships through ONE
# rt_chan_write call; at/above it, the scatter-gather begin/commit path
# copies each pickle-5 buffer straight into the slot (no join). Matches
# serialization.FRAME_OOB_MIN: the same payload-size regime where
# out-of-band starts beating in-band.
_SG_WRITE_MIN = 32 * 1024


class ShmChannel:
    def __init__(self, path: str, capacity: int, create: bool = False,
                 slots: int = 1):
        if slots < 1:
            raise ValueError(f"channel needs >= 1 slot, got {slots}")
        if capacity < 1:
            raise ValueError(f"channel needs capacity >= 1, got {capacity}")
        # round the slot capacity up to 8B so every slot's length word
        # (at 32 + i*(8+cap)) stays naturally aligned for the native
        # core's atomic u64 accesses — an unaligned atomic is UB
        # (SIGBUS on ARM, torn on a split cache line). The handle
        # carries the rounded value, so peers always agree.
        capacity = (capacity + 7) & ~7
        self.path = path
        self.capacity = capacity  # per-slot payload capacity
        self.slots = slots
        # Native core (C++ seqlock + futex handoff, native/src/
        # channel_core.cpp): same shm layout, so native and Python peers
        # interoperate; Python below is the fallback tier.
        self._native = None
        from ray_tpu import native as native_mod

        lib = native_mod.channel_lib()
        if lib is not None:
            handle = ctypes.c_void_p()
            rc = lib.rt_chan_open(
                path.encode(), capacity, slots, 1 if create else 0,
                ctypes.byref(handle),
            )
            if rc == 0:
                self._native = (lib, handle)
                return
            raise OSError(-rc, f"rt_chan_open({path!r}) failed")
        total = _HDR.size + slots * (8 + capacity)
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        if create:
            self._mm[: _HDR.size] = _HDR.pack(0, 0, slots, capacity)
            for suffix in (".d", ".a"):
                try:
                    os.mkfifo(path + suffix, 0o600)
                except FileExistsError:
                    pass
        elif (self._u64(16), self._u64(24)) != (slots, capacity):
            self._mm.close()
            raise ValueError(
                f"channel {path}: geometry mismatch — creator wrote "
                f"(slots={self._u64(16)}, cap={self._u64(24)}), attach "
                f"asked (slots={slots}, cap={capacity})"
            )
        # O_RDWR so neither side blocks in open() waiting for a peer
        self._dbell = os.open(path + ".d", os.O_RDWR | os.O_NONBLOCK)
        self._abell = os.open(path + ".a", os.O_RDWR | os.O_NONBLOCK)
        # a reader resumes from what has been CONSUMED (ack), not from the
        # latest seq — messages written before the reader attached (e.g.
        # dag.execute racing the exec loop's channel attach) must still be
        # delivered, in order
        self._last_read = self._u64(8)

    @classmethod
    def create(cls, capacity: int = 4 * 1024 * 1024,
               slots: int = 1) -> "ShmChannel":
        path = os.path.join(_SHM_DIR, f"rtchan_{uuid.uuid4().hex[:16]}")
        return cls(path, capacity, create=True, slots=slots)

    @classmethod
    def attach(cls, path: str, capacity: int, slots: int = 1) -> "ShmChannel":
        return cls(path, capacity, create=False, slots=slots)

    def handle(self):
        """Picklable (path, capacity, slots) to hand to the peer actor."""
        return {"path": self.path, "capacity": self.capacity,
                "slots": self.slots}

    @classmethod
    def from_handle(cls, handle) -> "ShmChannel":
        return cls.attach(handle["path"], handle["capacity"],
                          handle.get("slots", 1))

    def _u64(self, off: int) -> int:
        return int.from_bytes(self._mm[off: off + 8], "little")

    def _slot_off(self, msg: int) -> int:
        return _HDR.size + (msg % self.slots) * (8 + self.capacity)

    @staticmethod
    def _ring(fd: int) -> None:
        try:
            os.write(fd, b"\x01")
        except BlockingIOError:
            pass  # fifo full: peer has plenty of pending wakeups already

    @staticmethod
    def _drain(fd: int) -> None:
        try:
            os.read(fd, 64)
        except BlockingIOError:
            pass

    def _await(self, ready, bell_fd: int,
               deadline: Optional[float], what: str) -> None:
        """Hybrid wait for ``ready()``: spin on the shm header, then park
        on the doorbell fifo."""
        spin_until = time.monotonic() + _SPIN_S if _SPIN_S else 0.0
        while not ready():
            if _SPIN_S and time.monotonic() < spin_until:
                continue
            remaining = 0.05
            if deadline is not None:
                remaining = min(remaining, deadline - time.monotonic())
                if remaining <= 0:
                    raise TimeoutError(f"channel {self.path}: {what}")
            r, _, _ = select.select([bell_fd], [], [], max(remaining, 0.0))
            if r:
                self._drain(bell_fd)

    # -- writer --------------------------------------------------------

    def write(self, payload, timeout_s: Optional[float] = 60.0) -> None:
        self.write_views([payload], timeout_s)

    def write_views(self, parts: List[Any],
                    timeout_s: Optional[float] = 60.0) -> None:
        """Scatter-gather write: copy each buffer of ``parts`` into the
        next ring slot back-to-back (ONE host copy total — no join),
        then publish. Blocks while all ``slots`` slots hold unconsumed
        messages (the backpressure contract compiled DAGs rely on)."""
        views = serialization.byte_views(parts)
        total = sum(v.nbytes for v in views)
        if total > self.capacity:
            raise ValueError(
                f"payload {total} > channel slot capacity {self.capacity}"
            )
        if self._native is not None:
            lib, handle = self._native
            native_timeout = -1.0 if timeout_s is None else float(timeout_s)
            if total < _SG_WRITE_MIN:
                # small-message fast path: one native call beats the
                # begin/from_address/commit round trip, and the join of
                # a few KiB costs less than the extra ctypes hops (the
                # compiled_dag_call regime — scatter-gather only pays
                # once payloads carry real out-of-band buffers)
                data = b"".join(views) if len(views) != 1 else views[0]
                if not isinstance(data, bytes):
                    data = bytes(data)  # memoryview/bytearray → c_char_p
                rc = lib.rt_chan_write(handle, data, total, native_timeout)
            else:
                ptr = ctypes.c_void_p()
                rc = lib.rt_chan_write_begin(
                    handle, total, native_timeout, ctypes.byref(ptr),
                )
                if rc == 0:
                    dst = memoryview(
                        (ctypes.c_ubyte * total).from_address(ptr.value)
                    ).cast("B")
                    off = 0
                    for v in views:
                        dst[off: off + v.nbytes] = v
                        off += v.nbytes
                    rc = lib.rt_chan_write_commit(handle, total)
            if rc == -1:
                if core_metrics.ENABLED:
                    core_metrics.channel_write_blocks.inc(
                        tags={"transport": "shm"}
                    )
                raise TimeoutError(
                    f"channel {self.path}: ring full — reader never "
                    f"consumed (slots={self.slots})"
                )
            if rc != 0:
                raise ValueError(f"channel {self.path}: write error {rc}")
            return
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        seq = self._u64(0)  # single writer: only we advance it
        if core_metrics.ENABLED and not (seq - self._u64(8) < self.slots):
            # about to block on a full ring: writer-side backpressure
            core_metrics.channel_write_blocks.inc(tags={"transport": "shm"})
        # flow control: block while every slot holds an unconsumed message
        self._await(
            lambda: seq - self._u64(8) < self.slots, self._abell, deadline,
            f"ring full — reader never consumed (slots={self.slots})",
        )
        off = self._slot_off(seq)
        pos = off + 8
        for v in views:
            self._mm[pos: pos + v.nbytes] = v
            pos += v.nbytes
        self._mm[off: off + 8] = total.to_bytes(8, "little")
        # publish: bump seq last (release on x86/ARM via GIL + mmap)
        self._mm[0:8] = (seq + 1).to_bytes(8, "little")
        self._ring(self._dbell)

    def write_value(self, value: Any,
                    timeout_s: Optional[float] = 60.0) -> None:
        """Serialize ``value`` (pickle-5) and write its frame parts
        straight into the slot — header, meta and every out-of-band
        buffer land in shm with one copy each, no intermediate join.
        The reader's ``read_value`` (or ``serialization.unpack`` on a
        raw ``read``) inverts it."""
        meta, views = serialization.serialize(value)
        self.write_views(serialization.frame_parts(meta, views), timeout_s)

    # -- reader --------------------------------------------------------

    def read(self, timeout_s: Optional[float] = 30.0) -> bytes:
        """Block until the next unconsumed message arrives; messages are
        delivered strictly in publish order."""
        if self._native is not None:
            lib, handle = self._native
            ptr = ctypes.c_void_p()
            n = lib.rt_chan_read_begin(
                handle, -1.0 if timeout_s is None else float(timeout_s),
                ctypes.byref(ptr),
            )
            if n == -1:
                raise TimeoutError(f"channel {self.path}: no message")
            if n < 0:
                raise ValueError(f"channel {self.path}: read error {n}")
            # one copy out of the slot (the slot is recycled after commit,
            # so the caller must not alias it)
            data = ctypes.string_at(ptr.value, int(n)) if n else b""
            lib.rt_chan_read_commit(handle)
            return data
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        self._await(
            lambda: self._u64(0) > self._last_read, self._dbell, deadline,
            "no message",
        )
        off = self._slot_off(self._last_read)
        length = int.from_bytes(self._mm[off: off + 8], "little")
        data = bytes(self._mm[off + 8: off + 8 + length])
        self._last_read += 1
        self._mm[8:16] = self._last_read.to_bytes(8, "little")  # ack
        self._ring(self._abell)
        return data

    def read_value(self, timeout_s: Optional[float] = 30.0) -> Any:
        return serialization.unpack(self.read(timeout_s))

    def close(self, unlink: bool = False) -> None:
        """Idempotent: fds are nulled after the first close so a second
        call can never close an unrelated fd that reused the number."""
        if self._native is not None:
            lib, handle = self._native
            self._native = None
            lib.rt_chan_close(handle)
        elif hasattr(self, "_mm"):
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass
            for fd in (self._dbell, self._abell):
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
            self._dbell = self._abell = -1
        if unlink:
            unlink_channel(self.path)


def unlink_channel(path: str) -> None:
    """Remove a channel's shm segment and doorbell fifos (idempotent)."""
    for p in (path, path + ".d", path + ".a"):
        try:
            os.unlink(p)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Cross-host tier: RpcChannel (bounded mailbox over worker<->worker RPC)
# ---------------------------------------------------------------------------


class _RpcMailbox:
    """Receiver-side bounded queue for one RpcChannel."""

    __slots__ = ("q", "enq_seq", "consumed", "slots", "cv", "closed")

    def __init__(self, slots: int):
        self.q: deque = deque()
        self.enq_seq = 0  # highest seq accepted (writer seqs start at 1)
        self.consumed = 0
        self.slots = slots
        self.cv = threading.Condition()
        self.closed = False


_rpc_mailboxes: Dict[str, _RpcMailbox] = {}
_rpc_mailboxes_lock = threading.Lock()
# closed-channel tombstones: a writer retry racing close_rpc_mailbox
# must get a "closed" bounce, not a silently-recreated open mailbox
# that swallows its message. Trimmed FIFO so a long-lived process
# cannot grow unbounded (chan ids are one-shot uuids).
_rpc_closed: set = set()
_rpc_closed_order: deque = deque()
_RPC_CLOSED_CAP = 4096


def _mailbox(chan_id: str, slots: int) -> Optional[_RpcMailbox]:
    """Get-or-create: a writer's first push may land before the reader
    attaches (compiled-pipeline stage loops start in any order), so the
    mailbox materializes on first contact from either side. Returns
    None for a tombstoned (closed) channel."""
    with _rpc_mailboxes_lock:
        if chan_id in _rpc_closed:
            return None
        mb = _rpc_mailboxes.get(chan_id)
        if mb is None:
            mb = _RpcMailbox(slots)
            _rpc_mailboxes[chan_id] = mb
        return mb


def rpc_channel_deliver(chan_id: str, seq: int, payload,
                        slots: int) -> Dict[str, Any]:
    """The worker's ``rpc_chan_push`` lands here. Idempotent per seq
    (a writer retry after a lost ack re-sends the same seq); a full
    mailbox bounces with ``full`` and the writer retries — that bounce
    IS the cross-host backpressure."""
    mb = _mailbox(chan_id, slots)
    if mb is None:
        return {"status": "closed"}
    with mb.cv:
        if mb.closed:
            return {"status": "closed"}
        if seq <= mb.enq_seq:
            return {"status": "ok"}  # duplicate from a writer retry
        if len(mb.q) >= mb.slots:
            return {"status": "full"}
        mb.q.append(payload)
        mb.enq_seq = seq
        mb.cv.notify_all()
        return {"status": "ok"}


def close_rpc_mailbox(chan_id: str) -> None:
    with _rpc_mailboxes_lock:
        mb = _rpc_mailboxes.pop(chan_id, None)
        if chan_id not in _rpc_closed:
            _rpc_closed.add(chan_id)
            _rpc_closed_order.append(chan_id)
            while len(_rpc_closed_order) > _RPC_CLOSED_CAP:
                _rpc_closed.discard(_rpc_closed_order.popleft())
    if mb is not None:
        with mb.cv:
            mb.closed = True
            mb.cv.notify_all()


def rpc_channel_handle(reader_addr: str, capacity: int,
                       slots: int) -> Dict[str, Any]:
    """Mint a cross-host channel handle: the reader's worker RPC address
    plus geometry. No resource exists until the reader attaches or the
    writer's first push materializes the mailbox."""
    return {
        "kind": "rpc",
        "chan_id": f"rtchan_{uuid.uuid4().hex[:16]}",
        "addr": reader_addr,
        "capacity": capacity,
        "slots": slots,
    }


class RpcChannel:
    """Cross-host channel: same surface as ShmChannel, one ``chan_push``
    worker↔worker RPC per message. Payloads ≥ the multiseg floor ride
    as raw out-of-band segments via ``serialization.maybe_frame`` —
    the pipeline's stage-boundary activations never re-pickle in-band.
    Single writer, single reader; the reader must live in the process
    whose worker address is in the handle."""

    def __init__(self, handle: Dict[str, Any], role: str):
        if role not in ("read", "write"):
            raise ValueError(f"RpcChannel role must be read/write, not {role}")
        self._h = dict(handle)
        self.chan_id = handle["chan_id"]
        self.capacity = handle["capacity"]
        self.slots = handle["slots"]
        self.addr = handle["addr"]
        self.role = role
        self._seq = 0
        self._mb = None
        if role == "read":
            self._mb = _mailbox(self.chan_id, self.slots)
            if self._mb is None:
                raise ValueError(
                    f"channel {self.chan_id}: already closed (chan ids "
                    f"are one-shot)"
                )
        self._client = None

    # the handle mints attachments for either side
    def handle(self) -> Dict[str, Any]:
        return dict(self._h)

    def _rpc(self):
        if self._client is None:
            from ray_tpu.core import worker as worker_mod

            self._client = worker_mod.global_worker().workers.get(self.addr)
        return self._client

    # -- writer --------------------------------------------------------

    def write(self, payload, timeout_s: Optional[float] = 60.0) -> None:
        view = serialization.as_view(payload)
        if view.nbytes > self.capacity:
            raise ValueError(
                f"payload {view.nbytes} > channel capacity {self.capacity}"
            )
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        self._seq += 1
        wrapped = serialization.maybe_frame(
            payload if isinstance(payload, (bytes, bytearray)) else bytes(view)
        )
        backoff = 0.002
        while True:
            remaining = 30.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"channel {self.chan_id}: mailbox full — reader "
                        f"never consumed (slots={self.slots})"
                    )
            resp = self._rpc().call(
                "chan_push", chan_id=self.chan_id, seq=self._seq,
                payload=wrapped, slots=self.slots,
                timeout_s=max(1.0, min(remaining, 30.0)), retryable=False,
            )
            status = resp["status"]
            if status == "ok":
                return
            if status == "closed":
                raise ValueError(
                    f"channel {self.chan_id}: closed by the reader"
                )
            # full: bounded-mailbox backpressure. Back off exponentially
            # so a long consumer stall costs ~20 polls/s, not a 500/s
            # RPC storm against the receiver's dispatcher pool.
            if core_metrics.ENABLED:
                core_metrics.channel_write_blocks.inc(
                    tags={"transport": "rpc"}
                )
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)

    def write_views(self, parts: List[Any],
                    timeout_s: Optional[float] = 60.0) -> None:
        # cross-host: one contiguous frame per message (the join is the
        # price of the RPC tier; the frame itself still rides out-of-band)
        self.write(b"".join(serialization.byte_views(parts)), timeout_s)

    def write_value(self, value: Any,
                    timeout_s: Optional[float] = 60.0) -> None:
        meta, views = serialization.serialize(value)
        self.write_views(serialization.frame_parts(meta, views), timeout_s)

    # -- reader --------------------------------------------------------

    def read(self, timeout_s: Optional[float] = 30.0):
        """Returns bytes or a Frame (big payloads arrive out-of-band);
        ``serialization.unpack``/``as_view`` accept both."""
        mb = self._mb
        if mb is None:
            raise RuntimeError("write-side RpcChannel cannot read")
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        with mb.cv:
            while not mb.q:
                if mb.closed:
                    raise ValueError(
                        f"channel {self.chan_id}: closed"
                    )
                remaining = 1.0
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"channel {self.chan_id}: no message"
                        )
                mb.cv.wait(min(remaining, 1.0))
            payload = mb.q.popleft()
            mb.consumed += 1
        return payload

    def read_value(self, timeout_s: Optional[float] = 30.0) -> Any:
        return serialization.unpack(self.read(timeout_s))

    def close(self, unlink: bool = False) -> None:
        if self.role == "read":
            close_rpc_mailbox(self.chan_id)


def open_channel(handle: Dict[str, Any], role: str = "read"):
    """Attach to a channel from its handle — shm (same-host) or rpc
    (cross-host); compiled loops don't care which tier an edge rides."""
    if handle.get("kind") == "rpc":
        return RpcChannel(handle, role)
    return ShmChannel.from_handle(handle)
