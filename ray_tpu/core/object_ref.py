"""ObjectRef + ObjectRefGenerator: a first-class future naming an object owned by some worker.

Parity: ray.ObjectRef (python/ray/includes/object_ref.pxi). The ref carries
its owner's address so any holder can locate the value without a directory
lookup — the ownership model of the reference (src/ray/core_worker/
reference_counter.h:44). Refs are pickleable; deserializing one in another
process registers a borrow with the owner (round-1: release on driver GC).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.utils.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_address", "_weak")

    def __init__(self, object_id: ObjectID, owner_address: str = "", weak: bool = False):
        self.id = object_id
        self.owner_address = owner_address
        # weak refs don't participate in refcounting (internal bookkeeping)
        self._weak = weak
        if not weak:
            _get_tracker().add_local_ref(self)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self):
        return self.id.task_id()

    def job_id(self):
        return self.id.job_id()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Crossing a process boundary: create an in-flight pin at the owner
        # keyed by a fresh token; the deserializer's add_borrow consumes the
        # token so the pin transfers to the borrower (and is released when
        # the borrower's last local ref is GC'd).
        import uuid

        token = uuid.uuid4().hex
        _get_tracker().on_serialize(self, token)
        return (_deserialize_ref, (self.id, self.owner_address, token))

    def __del__(self):
        if not self._weak:
            try:
                _get_tracker().remove_local_ref(self)
            except Exception:
                pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        from ray_tpu.core import api

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def wait_thread():
            try:
                fut.set_result(api.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=wait_thread, daemon=True).start()
        return fut


def _deserialize_ref(
    object_id: ObjectID, owner_address: str, token: Optional[str] = None
) -> ObjectRef:
    # weak=False: the borrow must be released when the local ref is GC'd,
    # so the ref participates in local refcounting like any other.
    ref = ObjectRef(object_id, owner_address, weak=False)
    _get_tracker().on_deserialize(ref, token)
    return ref


class _NullTracker:
    def add_local_ref(self, ref):
        pass

    def remove_local_ref(self, ref):
        pass

    def on_serialize(self, ref, token):
        pass

    def on_deserialize(self, ref, token):
        pass


_null_tracker = _NullTracker()


def _get_tracker():
    """The current process's reference tracker (CoreWorker), if connected."""
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is None:
        return _null_tracker
    return w.reference_tracker


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded values (parity: the
    reference's streaming generators, num_returns="streaming" — dynamic
    return objects arrive as the executor produces them, long before the
    task finishes).

    Yields ObjectRefs in yield order; raises the task's error (if it
    failed) when iteration reaches it. Owner-process only (the consumer
    is the task's submitter)."""

    def __init__(self, task_id, worker):
        self._task_id = task_id
        self._worker = worker
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self.next_ref()

    def next_ref(self, timeout_s=None) -> "ObjectRef":
        import time as _time

        from ray_tpu.core import object_store as os_mod
        from ray_tpu.core.exceptions import GetTimeoutError, ObjectLostError
        from ray_tpu.utils.config import config
        from ray_tpu.utils.ids import ObjectID

        w = self._worker
        oid = ObjectID.from_task(self._task_id, self._i)
        done_oid = w._stream_done_oid(self._task_id)
        deadline = None if timeout_s is None else _time.monotonic() + timeout_s
        lost_deadline = None
        err_deadline = None
        while True:
            # Consult the final COUNT before yielding: a retried task can
            # leave stale items from the failed attempt at indices past
            # the final count — those must not be yielded. An Exception
            # marker, by contrast, raises only after the present prefix of
            # items has been consumed (they were validly produced).
            marker = w.memory_store.try_get(done_oid)
            has_marker = not os_mod.is_missing(marker)
            is_err = has_marker and isinstance(marker, Exception)
            if has_marker and not is_err and self._i >= int(marker):
                raise StopIteration
            if w.memory_store.contains(oid):
                self._i += 1
                return ObjectRef(oid, w.address)
            if is_err:
                # the error reply rides a different connection than the
                # in-order item pushes and can overtake them: give items
                # yielded before the failure a short grace to land
                if err_deadline is None:
                    err_deadline = (
                        _time.monotonic() + config.stream_error_grace_s
                    )
                elif _time.monotonic() > err_deadline:
                    raise marker
            if has_marker and not is_err:
                # count says item i exists but its push is still in
                # flight on another connection: give it a bounded grace —
                # the push can be silently lost (executor->owner link died
                # after the count reply landed), and an unbounded wait
                # would spin forever.
                if lost_deadline is None:
                    lost_deadline = (
                        _time.monotonic() + config.stream_item_grace_s
                    )
                elif _time.monotonic() > lost_deadline:
                    raise ObjectLostError(
                        f"streamed item {self._i} of task "
                        f"{self._task_id.hex()} was yielded but its value "
                        "never arrived (push lost)"
                    )
            if deadline is not None and _time.monotonic() > deadline:
                raise GetTimeoutError(
                    f"streamed item {self._i} of task "
                    f"{self._task_id.hex()} not available"
                )
            _time.sleep(0.005)

    def completed(self) -> bool:
        from ray_tpu.core import object_store as os_mod

        return not os_mod.is_missing(
            self._worker.memory_store.try_get(
                self._worker._stream_done_oid(self._task_id)
            )
        )
