"""Scheduling policies over the cluster resource view.

Parity: the reference's policy suite under src/ray/raylet/scheduling/policy/
— hybrid (hybrid_scheduling_policy.h:51 — prefer local under a utilization
threshold, then best-fit by score), spread, node-affinity, and the bundle
placement policies used by placement groups (PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD, bundle_scheduling_policy.cc). Policies are pure functions
over a view {node_id: {resources_total, resources_available, labels,
address}} so they are unit-testable without any cluster (reference test
style: src/ray/raylet/scheduling/tests/).

Scheduling strategies (parity: python/ray/util/scheduling_strategies.py):
  None | "DEFAULT"                              -> hybrid
  "SPREAD"                                      -> spread
  {"type": "node_affinity", "node_id", "soft"}  -> NodeAffinitySchedulingStrategy
  {"type": "placement_group", "pg_id", "bundle_index"}
                                                -> PlacementGroupSchedulingStrategy
  {"type": "node_label", "hard": {label: [values]}}
                                                -> NodeLabelSchedulingStrategy
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Tuple

SPREAD_THRESHOLD = 0.5  # utilization above which hybrid stops packing


def _fits(resources: Dict[str, float], available: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in resources.items() if v > 0)


def _feasible(resources: Dict[str, float], total: Dict[str, float]) -> bool:
    return all(total.get(k, 0.0) >= v for k, v in resources.items() if v > 0)


def _utilization(node: Dict[str, Any]) -> float:
    total = node["resources_total"]
    avail = node["resources_available"]
    utils = [
        1.0 - avail.get(k, 0.0) / v for k, v in total.items() if v > 0
    ]
    return max(utils) if utils else 0.0


def pg_bundle_of(strategy) -> Optional[Tuple[str, Optional[int]]]:
    if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
        return strategy["pg_id"], strategy.get("bundle_index")
    return None


def pick_node(
    view: Dict[str, Dict[str, Any]],
    resources: Dict[str, float],
    strategy: Any = None,
    pgs: Optional[Dict[str, Dict[str, Any]]] = None,
    pgs_lock=None,
    local_node_id: Optional[str] = None,
) -> Optional[str]:
    """Pick a node for one lease; None if nothing is feasible right now."""
    if isinstance(strategy, dict):
        kind = strategy.get("type")
        if kind == "node_affinity":
            target = strategy["node_id"]
            node = view.get(target)
            if node is not None and _fits(resources, node["resources_available"]):
                return target
            if node is not None and _feasible(resources, node["resources_total"]):
                return target  # queue on the target
            if strategy.get("soft"):
                return _hybrid(view, resources, local_node_id)
            return None
        if kind == "placement_group":
            pg_id = strategy["pg_id"]
            bundle_index = strategy.get("bundle_index")
            if pgs is None:
                return None
            if pgs_lock is not None:
                with pgs_lock:
                    pg = pgs.get(pg_id)
                    locations = dict(pg["bundle_locations"]) if pg else None
            else:
                pg = pgs.get(pg_id)
                locations = dict(pg["bundle_locations"]) if pg else None
            if not locations:
                return None
            if bundle_index is not None and bundle_index >= 0:
                return locations.get(bundle_index)
            # any bundle: pick one whose node still fits the request
            for idx in sorted(locations):
                node = view.get(locations[idx])
                if node and _fits(resources, node["resources_available"]):
                    return locations[idx]
            first = sorted(locations)[0] if locations else None
            return locations.get(first) if first is not None else None
        if kind == "node_label":
            hard = strategy.get("hard", {})
            candidates = {
                nid: n for nid, n in view.items()
                if all(n.get("labels", {}).get(k) in v for k, v in hard.items())
            }
            return _hybrid(candidates, resources, local_node_id)
    if strategy == "SPREAD":
        return _spread(view, resources)
    return _hybrid(view, resources, local_node_id)


# Above this cluster size, placement scores a random sample of nodes
# instead of the whole view (reference hybrid_scheduling_policy.h:51
# bounded top-k sampling): per-decision cost stays O(k) however many
# thousand nodes are registered, at the price of a near-optimal (not
# optimal) pick — with a full-scan fallback when the sample has no fit,
# so a nearly-full cluster still finds its last free node.
TOPK_SAMPLE = 32


def _sample_view(view: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    if len(view) <= TOPK_SAMPLE:
        return view
    keys = random.sample(list(view), TOPK_SAMPLE)
    return {k: view[k] for k in keys}


def _hybrid(
    view: Dict[str, Dict[str, Any]],
    resources: Dict[str, float],
    local_node_id: Optional[str] = None,
) -> Optional[str]:
    """Prefer local while below the spread threshold, else best-fit score;
    fall back to any feasible-by-total node (work will queue there)."""
    if local_node_id and local_node_id in view:
        node = view[local_node_id]
        if (
            _fits(resources, node["resources_available"])
            and _utilization(node) < SPREAD_THRESHOLD
        ):
            return local_node_id
    sampled = _sample_view(view)
    while True:
        fitting = [
            (nid, n) for nid, n in sampled.items()
            if _fits(resources, n["resources_available"])
        ]
        if fitting:
            # lowest utilization wins; tie-break randomly to avoid herding
            random.shuffle(fitting)
            fitting.sort(key=lambda kv: _utilization(kv[1]))
            return fitting[0][0]
        if sampled is not view:
            # sample had nothing with free capacity: full scan before
            # settling for a feasible-but-full node — on a busy cluster
            # the one free node is rarely in a 32-node sample
            sampled = view
            continue
        feasible = [
            nid for nid, n in sampled.items()
            if _feasible(resources, n["resources_total"])
        ]
        if feasible:
            return random.choice(feasible)
        return None


def _spread(view, resources) -> Optional[str]:
    sampled = _sample_view(view)
    fitting = [
        (nid, n) for nid, n in sampled.items()
        if _fits(resources, n["resources_available"])
    ]
    if not fitting and sampled is not view:
        fitting = [
            (nid, n) for nid, n in view.items()
            if _fits(resources, n["resources_available"])
        ]
    if not fitting:
        return _hybrid(view, resources)
    random.shuffle(fitting)
    fitting.sort(key=lambda kv: _utilization(kv[1]))
    return fitting[0][0]


# ---------------------------------------------------------------------------
# Placement-group bundle placement
# ---------------------------------------------------------------------------


def place_bundles(
    view: Dict[str, Dict[str, Any]],
    bundles: List[Dict[str, float]],
    strategy: str,
) -> Optional[Dict[int, str]]:
    """Map bundle index -> node_id, or None if infeasible right now."""
    nodes = {nid: dict(n, _avail=dict(n["resources_available"])) for nid, n in view.items()}

    def take(node, bundle) -> bool:
        if not _fits(bundle, node["_avail"]):
            return False
        for k, v in bundle.items():
            node["_avail"][k] = node["_avail"].get(k, 0.0) - v
        return True

    placement: Dict[int, str] = {}
    order = sorted(range(len(bundles)), key=lambda i: -sum(bundles[i].values()))

    if strategy in ("STRICT_PACK",):
        for nid, node in nodes.items():
            trial = dict(node, _avail=dict(node["_avail"]))
            if all(take(trial, bundles[i]) for i in order):
                return {i: nid for i in range(len(bundles))}
        return None

    if strategy in ("STRICT_SPREAD",):
        if len(bundles) > len(nodes):
            return None
        used = set()
        for i in order:
            chosen = None
            for nid, node in sorted(
                nodes.items(), key=lambda kv: _utilization(kv[1])
            ):
                if nid in used:
                    continue
                if take(node, bundles[i]):
                    chosen = nid
                    break
            if chosen is None:
                return None
            used.add(chosen)
            placement[i] = chosen
        return placement

    if strategy == "SPREAD":
        node_list = sorted(nodes.items(), key=lambda kv: _utilization(kv[1]))
        for pos, i in enumerate(order):
            chosen = None
            for offset in range(len(node_list)):
                nid, node = node_list[(pos + offset) % len(node_list)]
                if take(node, bundles[i]):
                    chosen = nid
                    break
            if chosen is None:
                return None
            placement[i] = chosen
        return placement

    # PACK (default): fill one node before moving to the next.
    for i in order:
        chosen = None
        for nid, node in sorted(
            nodes.items(), key=lambda kv: _utilization(kv[1]), reverse=True
        ):
            if take(node, bundles[i]):
                chosen = nid
                break
        if chosen is None:
            return None
        placement[i] = chosen
    return placement
