"""TPU-RDT: device-resident objects (ObjectRefs whose payload stays in HBM).

Parity target: Ray Direct Transport — the reference's GPUObjectManager
(/root/reference/python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:98) keeps tensors returned from
``@ray.method(tensor_transport=...)`` tasks inside the producing actor's
device memory; the ObjectRef that travels through the control plane is
pure metadata, and tensor payloads move out-of-band (collective / NIXL /
CUDA-IPC transports).

TPU-native design (NOT a port of the torch/NCCL machinery):

- A value produced under ``tensor_transport="device"`` is flattened with
  ``jax.tree_util``; ``jax.Array`` leaves stay in the producing process's
  HBM inside its :class:`DeviceObjectStore`, while the pytree skeleton
  (non-array leaves + treedef) is pickled into a small metadata record.
- The owner's memory store holds a :class:`DeviceValue` marker — shape/
  dtype avals only, no payload — so refcounting, borrows, and lineage
  work unchanged.
- Transfer tiers, chosen per consumer:
    1. **in-process**: the consuming task runs in the process that holds
       the value → the stored pytree is returned as-is (zero copy, the
       arrays never leave HBM; mutations are visible, exactly like the
       reference's documented RDT aliasing semantics).
    2. **cross-process**: the holder exports raw leaf bytes ONCE into an
       agent shm segment (worker.py _export_device_segment); same-host
       consumers mmap it, cross-host consumers stream it over the
       sendfile data plane, then ``jax.device_put`` — tensor data never
       passes through pickle.
  A jax.experimental.transfer (TransferServer) backend — true NIC/ICI DMA
  between jax clients, the NIXL analogue — slots in here once jaxlib's
  same-host path stops aborting (tracked: LocalBulkTransportFactory
  check-fail in jaxlib 0.9's CPU client); the RPC tier is the universal
  fallback the reference's object-store path plays.

Only fully-addressable (single-process) arrays take the device path;
arrays sharded across a multi-host mesh fall back to the ordinary object
path (their per-host shards belong to different processes by
construction in the multi-controller model).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.utils import serialization
from ray_tpu.utils.config import config

VALID_TRANSPORTS = ("object", "device")


def validate_transport(transport: str) -> str:
    """Reject unknown tensor_transport values at the API boundary (a typo
    must not silently fall back to the pickle path)."""
    if transport not in VALID_TRANSPORTS:
        raise ValueError(
            f"unknown tensor_transport {transport!r}; "
            f"expected one of {VALID_TRANSPORTS}"
        )
    return transport


def _meta_nbytes(leaves_meta: List[Tuple[Tuple[int, ...], str]]) -> int:
    import math

    import numpy as np

    return sum(
        math.prod(shape) * np.dtype(dtype).itemsize
        for shape, dtype in leaves_meta
    )


class DeviceValue:
    """Owner-side marker: 'payload lives in worker ``worker_address``'s
    device store under ``obj_hex``'. Analogue of GPUObjectMeta (reference
    gpu_object_manager.py:42): source actor + per-tensor avals."""

    __slots__ = ("worker_address", "obj_hex", "skeleton", "leaves_meta")

    def __init__(
        self,
        worker_address: str,
        obj_hex: str,
        skeleton: bytes,
        leaves_meta: List[Tuple[Tuple[int, ...], str]],
    ):
        self.worker_address = worker_address
        self.obj_hex = obj_hex
        self.skeleton = skeleton  # packed (treedef, static leaves)
        self.leaves_meta = leaves_meta  # [(shape, dtype_str)] per array leaf

    def nbytes(self) -> int:
        return _meta_nbytes(self.leaves_meta)


class _ArraySlot:
    """Placeholder marking an array leaf's position in the skeleton."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


def _is_device_array(x: Any) -> bool:
    import jax

    return isinstance(x, jax.Array)


def split_device_value(value: Any):
    """Flatten ``value``; pull out fully-addressable jax.Array leaves.

    Returns (arrays, skeleton_frame, leaves_meta) or None if the value
    holds no device arrays (caller falls back to the object path)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(value)
    arrays: List[Any] = []
    static: List[Any] = []
    for leaf in leaves:
        if _is_device_array(leaf) and leaf.is_fully_addressable:
            static.append(_ArraySlot(len(arrays)))
            arrays.append(leaf)
        else:
            static.append(leaf)
    if not arrays:
        return None
    skeleton = serialization.pack((treedef, static))
    leaves_meta = [(tuple(a.shape), str(a.dtype)) for a in arrays]
    return arrays, skeleton, leaves_meta


def join_device_value(skeleton: bytes, arrays: List[Any]) -> Any:
    """Inverse of :func:`split_device_value`."""
    import jax

    treedef, static = serialization.unpack(skeleton)
    leaves = [
        arrays[s.index] if isinstance(s, _ArraySlot) else s for s in static
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class DeviceObjectStore:
    """Per-process store of device-resident pytrees, keyed by object hex.

    The executor-side half of RDT (reference GPUObjectStore role): holds
    the actual ``jax.Array``s in HBM; serves raw buffer bytes to remote
    consumers; frees on the owner's release."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # obj_hex -> (arrays, skeleton, leaves_meta)
        self._objects: Dict[str, Tuple[List[Any], bytes, list]] = {}

    def put(self, obj_hex: str, value: Any) -> Optional[Tuple[bytes, list]]:
        """Store ``value``'s array leaves; return (skeleton, leaves_meta)
        or None when the value has no device arrays."""
        parts = split_device_value(value)
        if parts is None:
            return None
        arrays, skeleton, leaves_meta = parts
        with self._lock:
            self._objects[obj_hex] = (arrays, skeleton, leaves_meta)
        return skeleton, leaves_meta

    def get_value(self, obj_hex: str) -> Any:
        """In-process zero-copy read: rebuild the pytree around the SAME
        array objects (no transfer, no copy)."""
        with self._lock:
            arrays, skeleton, _ = self._objects[obj_hex]
        return join_device_value(skeleton, arrays)

    def arrays(self, obj_hex: str) -> List[Any]:
        """The live device arrays (for the shm/data-plane export path)."""
        with self._lock:
            arrays, _, _ = self._objects[obj_hex]
        return arrays

    def free(self, obj_hex: str) -> None:
        with self._lock:
            self._objects.pop(obj_hex, None)
            self._cv.notify_all()

    def contains(self, obj_hex: str) -> bool:
        with self._lock:
            return obj_hex in self._objects

    def wait_freed(self, obj_hex: str, timeout_s: Optional[float] = None) -> bool:
        """Block until the object is freed (parity: wait_tensor_freed,
        reference gpu_object_manager.py:70 — lets an actor know when a
        returned tensor is safe to mutate again)."""
        import time

        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while obj_hex in self._objects:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(remaining if remaining is not None else 1.0)
            return True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            objs = list(self._objects.values())
        total = sum(_meta_nbytes(leaves_meta) for _, _, leaves_meta in objs)
        return {"device_objects": len(objs), "device_bytes": total}


# ---------------------------------------------------------------------------
# Overlapped export: double-buffered chunked D2H -> shm/socket write
# ---------------------------------------------------------------------------
#
# The export path (worker._build_device_export) used to be strictly
# serial: D2H-convert EVERY leaf, then pwrite every byte. Here the two
# halves pipeline through a depth-2 staging queue (the double buffer):
# a producer thread issues ``copy_to_host_async`` for leaf i+1 before
# materializing leaf i and emits (offset, host-view) chunks of
# ``rdt_d2h_chunk_bytes``; the caller thread pwrites chunk k-1 while
# chunk k's device->host copy is in flight — the nixl_tensor_transport
# playbook of hiding the transfer behind the write (and vice versa).
# On a TPU the D2H is a real DMA and the overlap is wall-clock; on the
# CPU backend np.asarray is a zero-copy view, so the win there comes
# from the producer-side EAGER export instead (worker._package_returns
# kicks this machinery the moment a device return is parked, so the
# whole export overlaps the consumer task's submit/schedule latency
# rather than sitting on its first-get critical path).


def plan_export_layout(arrays: List[Any]) -> Tuple[List[int], int]:
    """64B-aligned segment offsets for each leaf (from aval nbytes — no
    materialization) and the total segment size."""
    offsets: List[int] = []
    off = 0
    for a in arrays:
        off = (off + 63) & ~63  # 64B-align each leaf for frombuffer
        offsets.append(off)
        off += a.nbytes
    return offsets, max(off, 1)


def _stage_chunks(arrays, offsets, chunk_bytes, emit) -> None:
    """D2H-convert each leaf (async-prefetching the next) and emit
    (file_offset, host_byte_view) pieces of at most ``chunk_bytes``."""
    import numpy as np

    for i, a in enumerate(arrays):
        if i + 1 < len(arrays):
            nxt = arrays[i + 1]
            if hasattr(nxt, "copy_to_host_async"):
                try:
                    nxt.copy_to_host_async()
                except Exception:  # noqa: BLE001 — optional fast path
                    pass
        host = np.ascontiguousarray(np.asarray(a))
        mv = memoryview(host).cast("B")
        base = offsets[i]
        for lo in range(0, mv.nbytes, chunk_bytes):
            emit((base + lo, mv[lo: lo + chunk_bytes]))


def write_arrays_overlapped(fd: int, arrays: List[Any],
                            offsets: List[int]) -> None:
    """Write every leaf's bytes at its offset, overlapping the D2H of
    chunk k with the pwrite of chunk k-1 through a depth-2 queue.
    Falls back to the serial convert-then-write loop when
    ``rdt_d2h_overlap`` is off (or there is nothing to overlap)."""
    from ray_tpu.core.object_store import _pwrite_all

    chunk_bytes = max(64 * 1024, int(config.rdt_d2h_chunk_bytes))
    if arrays and hasattr(arrays[0], "copy_to_host_async"):
        try:
            arrays[0].copy_to_host_async()
        except Exception:  # noqa: BLE001 — optional fast path
            pass
    if not config.rdt_d2h_overlap or not arrays:
        _stage_chunks(arrays, offsets, chunk_bytes,
                      lambda item: _pwrite_all(fd, item[1], item[0]))
        return
    q: "queue.Queue" = queue.Queue(maxsize=2)  # the double buffer
    stop = threading.Event()

    def _emit(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue
        raise RuntimeError("export cancelled")  # consumer bailed

    def _produce():
        try:
            _stage_chunks(arrays, offsets, chunk_bytes, _emit)
            _emit(None)
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            try:
                _emit(e)
            except RuntimeError:
                pass

    t = threading.Thread(target=_produce, daemon=True,
                         name="rt-rdt-d2h")
    t.start()
    try:
        while True:
            item = q.get()
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            off, view = item
            _pwrite_all(fd, view, off)
    finally:
        stop.set()
        while True:  # unblock a producer parked on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=10.0)


