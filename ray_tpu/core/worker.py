"""CoreWorker — the per-process runtime (driver and workers alike).

Parity: the reference CoreWorker (src/ray/core_worker/core_worker.h:167 —
Put :486, Get :662, Wait :702, CreateActor :884, SubmitActorTask :952), its
in-process memory store (store_provider/memory_store/), ownership tracking
(reference_counter.h:44), task submission (normal_task_submitter.h:124,
actor_task_submitter.h with per-caller ordering) and task execution
(task_execution/task_receiver.h + ordered actor queues).

Ownership model: the process that creates an object (by put or by task
submission) owns it — stores the value (or its plasma marker), serves
get_object to borrowers, and decides deletion. Refs crossing process
boundaries use the token-based borrow protocol (ReferenceTracker): each
serialization creates a TTL-bounded in-flight pin at the owner that the
deserializer consumes into a real borrow, released when the borrower's
last local ref drops.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import traceback
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import object_store as os_mod
from ray_tpu.core import runtime_env as runtime_env_mod
from ray_tpu.core.device_objects import DeviceValue
from collections import OrderedDict, deque

from ray_tpu.core.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import (
    LostValue,
    MemoryStore,
    PlasmaValue,
    ShmClient,
    pwritev_all,
)
from ray_tpu.core.task import TaskOptions, TaskSpec
from ray_tpu.observability import core_metrics, forensics, profiler, tracing
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config
from ray_tpu.utils.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.utils.rpc import (
    ClientPool,
    RemoteError,
    RpcClient,
    RpcConnectionError,
    RpcError,
    RpcServer,
    RpcTimeout,
)

logger = logging.getLogger(__name__)

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()


def global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first."
        )
    return _global_worker


def global_worker_or_none() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(w: Optional["CoreWorker"]) -> None:
    global _global_worker
    with _global_lock:
        _global_worker = w


class ReferenceTracker:
    """Per-process ref bookkeeping (reference: reference_counter.h:44).

    Borrow protocol (token-based, replaces round-1 permanent escape
    pinning): every serialization of a ref creates an *in-flight pin* at
    the owner, tagged with a fresh token. The deserializer's add_borrow
    *consumes* the token — transferring the pin to the borrower — so the
    pin lives exactly as long as the borrow. A ref serialized but never
    deserialized (e.g. task args whose lease failed) would leak its pin;
    in-flight pins therefore carry a TTL (config.borrow_pin_ttl_s) and are
    swept opportunistically on tracker activity — the lightweight stand-in
    for the reference's task-completion borrow reports.

    Args of still-pending tasks are additionally guarded by a
    TASK-PENDENCY BORROW (the reference achieves this with
    task-completion borrow reports, reference_counter.h:44): when packing
    a task's args the submitter takes one plain borrow per serialized ref
    and releases it when the task reaches a terminal state. Unlike the
    in-flight token (consumed by the first deserialization), the pendency
    borrow survives retries — a ref arg stays alive across a lease-queue
    wait longer than the TTL AND between attempts of a retried task.
    """

    def __init__(self, worker: "CoreWorker"):
        self._worker = worker
        self._lock = threading.Lock()
        self._local_counts: Dict[ObjectID, int] = {}
        self._borrows: Dict[ObjectID, int] = {}  # owner side: remote borrowers
        # owner side: in-flight pins, token -> (oid, created_at monotonic)
        self._escape_tokens: Dict[str, Tuple[ObjectID, float]] = {}
        # serializer side: per-thread capture of refs serialized while
        # packing task args (worker._pack_task_args)
        self._capture = threading.local()
        self._next_sweep = 0.0
        # Tokens whose consume arrived before their register (one-way RPCs
        # on different sockets have no cross-connection ordering): a later
        # register for one of these must be dropped, not pinned forever.
        self._consumed_tokens: "OrderedDict[str, None]" = OrderedDict()
        self._borrow_sends: Dict[ObjectID, int] = {}  # borrower side: add_borrows sent

    def _remember_consumed_locked(self, token: str) -> None:
        self._consumed_tokens[token] = None
        while len(self._consumed_tokens) > 65536:
            self._consumed_tokens.popitem(last=False)

    def stats(self) -> Dict[str, Any]:
        """Reference-state snapshot for the state API: per-object local
        ref counts, outstanding remote borrows, and in-flight pins with
        their oldest age (a pin far past the TTL is a leaked borrow)."""
        now = time.monotonic()
        with self._lock:
            inflight: Dict[str, Dict[str, Any]] = {}
            for oid, created in self._escape_tokens.values():
                rec = inflight.setdefault(
                    oid.hex(), {"count": 0, "oldest_age_s": 0.0}
                )
                rec["count"] += 1
                rec["oldest_age_s"] = max(
                    rec["oldest_age_s"], round(now - created, 3)
                )
            return {
                "address": self._worker.address,
                "local_refs": {
                    o.hex(): n for o, n in self._local_counts.items() if n
                },
                "borrows": {
                    o.hex(): n for o, n in self._borrows.items() if n
                },
                "inflight_pins": inflight,
            }

    def add_local_ref(self, ref: ObjectRef) -> None:
        with self._lock:
            self._local_counts[ref.id] = self._local_counts.get(ref.id, 0) + 1

    def remove_local_ref(self, ref: ObjectRef) -> None:
        delete = False
        release = None
        with self._lock:
            count = self._local_counts.get(ref.id, 0) - 1
            if count <= 0:
                self._local_counts.pop(ref.id, None)
                if self._worker.owns(ref):
                    if not self._borrows.get(ref.id):
                        delete = True
                else:
                    release = self._borrow_sends.pop(ref.id, 0)
            else:
                self._local_counts[ref.id] = count
        if delete:
            self._worker.delete_owned_object(ref.id)
        elif release:
            self._worker.send_release_borrow(ref.owner_address, ref.id, n=release)
        self.sweep_expired_pins()

    def on_serialize(self, ref: ObjectRef, token: str) -> None:
        """A ref is crossing a process boundary: pin the object at the
        owner for the duration of the flight, keyed by token."""
        owned = self._worker.owns(ref)
        items = getattr(self._capture, "items", None)
        if items is not None:
            items.append((ref.owner_address, ref.id, owned))
        if owned:
            with self._lock:
                self._escape_tokens[token] = (ref.id, time.monotonic())
                self._borrows[ref.id] = self._borrows.get(ref.id, 0) + 1
            self.sweep_expired_pins()
        else:
            self._worker.send_add_borrow(
                ref.owner_address, ref.id, register_token=token
            )

    def begin_capture(self) -> None:
        """Start recording refs serialized by on_serialize on this thread."""
        self._capture.items = []

    def end_capture(self) -> List[Tuple[str, ObjectID, bool]]:
        """Stop recording; return [(owner_address, oid, owned)]."""
        items = getattr(self._capture, "items", None) or []
        self._capture.items = None
        return items

    def add_task_borrow(self, oid: ObjectID) -> None:
        """Owner-side pendency borrow: keep an owned ref arg alive while
        its task is pending (released via owner_release_borrow)."""
        with self._lock:
            self._borrows[oid] = self._borrows.get(oid, 0) + 1

    def on_deserialize(self, ref: ObjectRef, token: Optional[str]) -> None:
        """A ref arrived from another process; take over its in-flight pin
        (or add a fresh borrow if the token was already consumed)."""
        if self._worker.owns(ref):
            # Our own ref came back: the local count now guards it.
            consume = False
            with self._lock:
                if token is not None:
                    if token in self._escape_tokens:
                        del self._escape_tokens[token]
                        consume = True
                    else:
                        # The serializer's register (a one-way RPC on another
                        # socket) hasn't landed yet: remember the token so the
                        # late register is dropped instead of pinning forever.
                        self._remember_consumed_locked(token)
            if consume:
                self.owner_release_borrow(ref.id)
            return
        with self._lock:
            self._borrow_sends[ref.id] = self._borrow_sends.get(ref.id, 0) + 1
        self._worker.send_add_borrow(
            ref.owner_address, ref.id, consume_token=token
        )

    def owner_add_borrow(
        self,
        oid: ObjectID,
        register_token: Optional[str] = None,
        consume_token: Optional[str] = None,
    ) -> None:
        with self._lock:
            if consume_token is not None:
                if consume_token in self._escape_tokens:
                    # Transfer the in-flight pin to this borrower: no increment.
                    del self._escape_tokens[consume_token]
                    return
                # Consume beat its register (no cross-socket ordering):
                # count this borrower now and remember the token so the
                # late register is dropped instead of pinning forever.
                self._remember_consumed_locked(consume_token)
            if register_token is not None:
                if register_token in self._consumed_tokens:
                    # The deserializer already took (and counted) this pin.
                    return
                self._escape_tokens[register_token] = (oid, time.monotonic())
            self._borrows[oid] = self._borrows.get(oid, 0) + 1
        self.sweep_expired_pins()

    def owner_release_borrow(self, oid: ObjectID, n: int = 1) -> None:
        delete = False
        with self._lock:
            remaining = self._borrows.get(oid, 0) - n
            if remaining <= 0:
                self._borrows.pop(oid, None)
                if not self._local_counts.get(oid):
                    delete = True
            else:
                self._borrows[oid] = remaining
        if delete and self._worker.owns_id(oid):
            # If the producing task hasn't stored the result yet, the store
            # hook (maybe_delete_unreferenced at _store_task_reply) catches
            # the release-before-store ordering.
            self._worker.delete_owned_object(oid)

    def sweep_expired_pins(self) -> None:
        """Release in-flight pins whose token was never consumed within the
        TTL (serialized-but-never-deserialized refs — lease failures,
        dropped messages). Rate-limited to one sweep per TTL/4."""
        ttl = float(config.borrow_pin_ttl_s)
        now = time.monotonic()
        expired: List[ObjectID] = []
        with self._lock:
            if now < self._next_sweep:
                return
            self._next_sweep = now + ttl / 4
            for token, (oid, created) in list(self._escape_tokens.items()):
                if now - created > ttl:
                    del self._escape_tokens[token]
                    expired.append(oid)
        for oid in expired:
            self.owner_release_borrow(oid)

    def maybe_delete_unreferenced(self, oid: ObjectID) -> bool:
        """True if nothing (local refs, borrows, in-flight pins) can ever
        reach this object — called when a task result lands after all its
        refs were already dropped."""
        with self._lock:
            return not self._local_counts.get(oid) and not self._borrows.get(oid)


class _ActorRuntime:
    """Executor-side state when this worker hosts an actor."""

    def __init__(self, actor_id: str, instance, max_concurrency: int,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 method_groups: Optional[Dict[str, str]] = None):
        self.actor_id = actor_id
        self.instance = instance
        self.max_concurrency = max_concurrency
        # Concurrency groups (reference
        # task_execution/concurrency_group_manager.h:38): each named
        # group gets its OWN queue + thread pool sized to its limit, so a
        # saturated "io" group can never starve "compute" — ungrouped
        # methods ride the default pool of max_concurrency threads.
        self.queue: "queue.Queue" = queue.Queue()  # default group
        self.group_queues: Dict[str, "queue.Queue"] = {
            g: queue.Queue() for g in (concurrency_groups or {})
        }
        self.group_limits: Dict[str, int] = dict(concurrency_groups or {})
        self.method_groups: Dict[str, str] = dict(method_groups or {})
        self.threads: List[threading.Thread] = []
        self.running = 0  # executions in flight (guarded by running_lock)
        self.running_lock = threading.Lock()
        # Direct-call concurrency bound (rpc_actor_direct_call): direct
        # dispatches run on the RPC dispatcher pool, not the executor
        # threads, so they need their OWN max_concurrency gate — without
        # it the serve proxy's hot path would run a max_concurrency=1
        # deployment's callable concurrently. (Mixed handle+direct
        # traffic can still reach 2x the bound — one per path — which
        # serve replicas tolerate; handle-only or proxy-only traffic,
        # the common cases, see exactly max_concurrency.)
        self.direct_sem = threading.BoundedSemaphore(max(1, max_concurrency))
        # Lazily-started asyncio loop for `async def` methods (reference:
        # async actors run coroutines on one event loop, task_execution
        # fiber/async queues): coroutines are scheduled here and the reply
        # is sent from a done-callback, so thousands of IO-bound calls
        # overlap without occupying executor threads.
        self.loop = None
        self.loop_lock = threading.Lock()
        # async mode: ANY coroutine method makes every call run on the
        # event loop (set at creation from the instance's methods)
        import inspect

        self.is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(instance, callable)
        )

    def queue_for(self, method_name: str) -> "queue.Queue":
        group = self.method_groups.get(method_name)
        if group is not None and group in self.group_queues:
            return self.group_queues[group]
        return self.queue

    def total_queued(self) -> int:
        return self.queue.qsize() + sum(
            q.qsize() for q in self.group_queues.values()
        )

    def ensure_loop(self):
        import asyncio

        with self.loop_lock:
            if self.loop is None:
                self.loop = asyncio.new_event_loop()
                t = threading.Thread(
                    target=self.loop.run_forever,
                    name="actor-asyncio", daemon=True,
                )
                t.start()
                self.threads.append(t)
            return self.loop


class CoreWorker:
    def __init__(
        self,
        mode: str,  # "driver" | "worker"
        control_address: str,
        node_agent_address: str,
        session_id: str,
        node_id_hex: str,
        job_id: Optional[JobID] = None,
    ):
        self.mode = mode
        self.worker_id = WorkerID.from_random()
        self.session_id = session_id
        self.node_id_hex = node_id_hex
        self.control_address = control_address
        self.node_agent_address = node_agent_address

        self.server = RpcServer(f"{mode}-worker")
        self.server.register_instance(self)
        self.server.register_raw("actor_task", self._raw_actor_task)
        self.server.start()

        from ray_tpu.core.ha import head_resolver

        self.control = RpcClient(
            control_address, name=f"{mode}->cs", resolver=head_resolver()
        )
        self.agent = RpcClient(node_agent_address, name=f"{mode}->agent")
        self.workers = ClientPool("w2w")
        self.agents = ClientPool("w2agent")

        self.memory_store = MemoryStore()
        self.shm = ShmClient()
        # deferred segment reclaim: private segments whose DELETE arrived
        # while live views (arrays a get() returned) still pinned the
        # mapping — in `get(put(x))` the value dies a beat AFTER the ref,
        # so recycling at ref-death would always miss. Entries are
        # [oid_hex, path, attempts]; flushed before the next plasma
        # create (the previous iteration's views are dead by then), so a
        # put/delete loop reuses its own warm pages.
        self._pending_reclaim: deque = deque()
        self._pending_reclaim_lock = threading.Lock()
        # data-plane port cache per agent: addr -> (port, fetched_at);
        # entries expire so an agent restart gets re-discovered
        self._data_ports: Dict[str, Tuple[int, float]] = {}
        # TPU-RDT: lazily-built store of device-resident pytrees this
        # process produced under tensor_transport="device"
        self._device_store = None
        self._device_store_lock = threading.Lock()
        # obj_hex -> export meta dict: device leaves exported once into a
        # local-agent shm segment, then served zero-copy (same host) or
        # over the sendfile data plane (cross host)
        self._device_exports: Dict[str, Dict[str, Any]] = {}
        self._device_exports_lock = threading.Lock()
        # eager-export throttle: at most this many background D2H+write
        # threads at once; past it, exports stay lazy (consumer's first
        # get builds them) instead of queueing unbounded work
        self._eager_export_sem = threading.BoundedSemaphore(2)
        # remote-driver (gateway) mode: set by enable_gateway_mode()
        self._public_address: Optional[str] = None
        self._remote_driver = False
        self._reverse_listener = None
        self.reference_tracker = ReferenceTracker(self)

        self.job_id = job_id or JobID.nil()
        self.driver_task_id: Optional[TaskID] = None
        self._task_index_lock = threading.Lock()
        self._put_index = 0

        self._registered_fns: set = set()
        self._fn_cache: Dict[str, Any] = {}

        self._submit_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="submit"
        )
        # Owner-side task dependency resolution (reference
        # local_dependency_resolver.h): started lazily on the first task
        # submitted with a pending ObjectRef arg.
        self._dep_resolver: Optional[_DependencyResolver] = None
        self._dep_resolver_lock = threading.Lock()
        # actor tasks: task_id hex -> pending top-level ObjectRef args,
        # awaited by the actor sender thread before the send
        self._pending_task_deps: Dict[str, List[ObjectRef]] = {}
        # per-actor ordered senders + address cache
        self._actor_senders: Dict[str, "_ActorSender"] = {}
        self._actor_senders_lock = threading.Lock()
        # per-scheduling-key lease-caching normal-task submitters
        # (reference normal_task_submitter.h:52-82), swept by ONE shared
        # janitor thread (started with the first submitter)
        self._task_submitters: Dict[tuple, "_NormalTaskSubmitter"] = {}
        self._task_submitters_lock = threading.Lock()
        self._submitter_janitor: Optional[threading.Thread] = None
        self._actor_addr_cache: Dict[str, str] = {}
        # lifecycle batching (ISSUE 14): created on first use when
        # actor_batch_flush_ms > 0; kill_actor records the id here so a
        # task submitted right after a (still-queued) kill fails with
        # ActorDiedError deterministically instead of racing the flush
        self._lifecycle_batcher: Optional[_ActorLifecycleBatcher] = None
        self._lifecycle_batcher_lock = threading.Lock()
        self._locally_killed: set = set()

        self._actor_runtime: Optional[_ActorRuntime] = None
        self._current_ctx = threading.local()
        self._shutdown = threading.Event()

        # cancellation + bookkeeping of in-flight executions
        self._running_tasks: Dict[str, Dict[str, Any]] = {}
        self._cancelled_tasks: set = set()
        # owner side: task_id hex -> worker address currently executing it
        self._inflight_push: Dict[str, str] = {}
        # submitter side: task_id hex -> [(owner_address, ObjectID, owned)]
        # pendency borrows protecting the task's serialized args until it
        # reaches a terminal state
        self._arg_pins: Dict[str, List[Tuple[str, ObjectID, bool]]] = {}
        # actors whose init-arg borrows must outlive the first ALIVE
        # observation (max_restarts != 0: restarts re-read the init args)
        self._restartable_actor_inits: set = set()
        self._reattach_lock = threading.Lock()
        # lineage (reference object_recovery_manager.h:26 + task_manager.h
        # lineage bookkeeping): task_id hex -> [spec, strategy,
        # live_return_count] for re-executing the creating task when its
        # objects are lost. Bounded by BYTES of retained arg frames (the
        # reference bounds lineage the same way) as well as entry count;
        # entries drop when every return of the task has been deleted.
        self._lineage: "OrderedDict[str, List[Any]]" = OrderedDict()
        self._lineage_bytes = 0
        self._lineage_lock = threading.Lock()
        # single-flight guard: task_id hex -> Event set when re-execution done
        self._reconstructing: Dict[str, threading.Event] = {}
        # actor_id -> max_task_retries (lazily fetched from the actor record)
        self._actor_retry_cache: Dict[str, int] = {}
        # task execution events for the timeline (reference
        # task_event_buffer.cc -> GcsTaskManager -> `ray timeline`):
        # bounded ring of execution slices {name, task_id, ts_us, dur_us}
        # plus lifecycle instants (observability/tracing.py). Evictions
        # are counted so a truncated timeline is detectable.
        self._task_events: deque = deque(maxlen=10000)
        self._task_events_dropped = 0

    # ------------------------------------------------------------------
    # identity / context
    # ------------------------------------------------------------------

    @property
    def address(self) -> str:
        # remote-driver mode: advertise the gateway-side reverse address
        # (cluster peers cannot reach a NAT'd driver directly)
        if self._public_address is not None:
            return self._public_address
        return self.server.address

    def owns(self, ref: ObjectRef) -> bool:
        return ref.owner_address == self.address

    def owns_id(self, oid: ObjectID) -> bool:
        """True if this worker is the owner of an object it stores locally
        (used when only the id, not a ref with owner address, is at hand)."""
        return self.memory_store.contains(oid)

    def current_task_id(self) -> Optional[TaskID]:
        return getattr(self._current_ctx, "task_id", None) or self.driver_task_id

    def current_actor_id(self) -> Optional[str]:
        if self._actor_runtime is not None:
            return self._actor_runtime.actor_id
        return None

    def current_job_id(self) -> JobID:
        ctx_job = getattr(self._current_ctx, "job_id", None)
        return ctx_job or self.job_id

    def _next_task_id(self) -> TaskID:
        return TaskID.for_normal_task(self.current_job_id())

    # ------------------------------------------------------------------
    # connection bring-up
    # ------------------------------------------------------------------

    def connect_driver(self) -> None:
        job_hex = self.control.call(
            "register_job", driver_address=self.address, metadata={"pid": os.getpid()},
            retryable=True,
        )
        self.job_id = JobID.from_hex(job_hex)
        self.driver_task_id = TaskID.for_driver(self.job_id)
        self._subscribe_actor_updates()

    def _subscribe_actor_updates(self) -> None:
        """Track actor address changes via control-store pubsub (parity:
        callers resolve actor location via GCS subscribe, SURVEY.md §3.3)."""

        def on_pubsub(payload):
            topic, data = payload
            if topic != "actor":
                return
            aid = data.get("actor_id")
            if not aid:
                return
            if data.get("state") == "ALIVE" and data.get("worker_address"):
                self._actor_addr_cache[aid] = data["worker_address"]
            else:
                self._actor_addr_cache.pop(aid, None)

        self.control.on_push("pubsub", on_pubsub)
        self.control.call("subscribe", topics=["actor"], retryable=True)
        # Subscriptions are connection-scoped server state: after a head
        # bounce the (re-attached) connection must re-assert them, and the
        # address cache may be stale for anything that moved meanwhile.
        def resubscribe():
            self._actor_addr_cache.clear()
            self.control.call("subscribe", topics=["actor"], timeout_s=10.0)

        self.control.add_reconnect_callback(resubscribe)

    def enable_gateway_mode(self) -> None:
        """Remote-driver mode (reference ray:// client,
        util/client/ARCHITECTURE.md): this driver reaches the cluster
        only through the head gateway. Outbound connections tunnel
        (rpc.py connect); inbound peers reach us via a gateway-side
        reverse bind whose address we advertise; and shm paths are never
        local, so big objects stay in the memory store and plasma reads
        always take the chunked/data-plane pull."""
        from ray_tpu.utils import gateway as gateway_mod

        self._remote_driver = True
        rl = gateway_mod.ReverseListener(
            self.server, f"drv-{self.worker_id.hex()[:12]}"
        )
        self._public_address = rl.start()
        self._reverse_listener = rl

    def connect_worker(self) -> None:
        self.agent.call(
            "register_worker",
            worker_id=self.worker_id.hex(),
            address=self.address,
            pid=os.getpid(),
            kind=getattr(self, "worker_kind", "cpu"),
            env_hash=getattr(self, "boot_env_hash", ""),
            retryable=True,
        )
        self._subscribe_actor_updates()
        t = threading.Thread(target=self._agent_watchdog, name="agent-watch", daemon=True)
        t.start()
        if forensics.ENABLED and float(config.task_stall_dump_s) > 0:
            threading.Thread(
                target=self._stall_watchdog, name="stall-watch",
                daemon=True,
            ).start()
        profiler.maybe_start_continuous()

    def _stall_watchdog(self) -> None:
        """Flag tasks running past ``task_stall_dump_s``: ONE
        ``{"type": "stall"}`` event per task occurrence, carrying the
        stuck thread's stack into the event ring (forensics)."""
        threshold = float(config.task_stall_dump_s)
        period = min(max(threshold / 4.0, 0.05), 2.0)
        stamped: set = set()
        while not self._shutdown.wait(period):
            now = time.monotonic()
            for tid_hex, info in list(self._running_tasks.items()):
                t0 = info.get("t0")
                if t0 is None or now - t0 < threshold \
                        or tid_hex in stamped:
                    continue
                stamped.add(tid_hex)
                if forensics.ENABLED:
                    forensics.stamp_stall(
                        task_id=tid_hex,
                        name=info.get("name", ""),
                        elapsed_s=now - t0,
                        thread_ident=info.get("tid"),
                        worker_address=self.address,
                    )
            # forget finished tasks so the one-shot set stays bounded
            stamped &= set(self._running_tasks)

    def _agent_watchdog(self) -> None:
        """Exit if the node agent goes away (orphan prevention: a node's
        workers die with the node, as the reference raylet guarantees)."""
        failures = 0
        while not self._shutdown.wait(2.0):
            try:
                self.agent.call("store_usage", timeout_s=5.0)
                failures = 0
            except RpcConnectionError:
                # connection refused/reset: the agent process is gone
                failures += 3
            except RpcError:
                # slow but alive (CPU contention): be patient
                failures += 1
            if failures >= 3:
                logger.warning("node agent unreachable; worker exiting")
                os._exit(1)

    def shutdown(self) -> None:
        if self._reverse_listener is not None:
            try:
                self._reverse_listener.stop()
            except Exception:  # noqa: BLE001 — teardown path
                pass
        self._shutdown.set()
        if self._lifecycle_batcher is not None:
            # ship still-queued registrations/kills before the control
            # connection goes away
            self._lifecycle_batcher.close()
        self._submit_pool.shutdown(wait=False)
        self.server.stop()
        self.control.close()
        self.agent.close()
        self.workers.close_all()
        self.agents.close_all()
        self.shm.close()

    # ------------------------------------------------------------------
    # function table
    # ------------------------------------------------------------------

    def register_function(self, fn_id: str, blob: bytes, name: str) -> None:
        if fn_id in self._registered_fns:
            return
        self.control.call("kv_put", ns="fn", key=fn_id, value=blob, overwrite=False,
                          retryable=True)
        self._registered_fns.add(fn_id)

    def load_function(self, fn_id: str):
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            blob = self.control.call("kv_get", ns="fn", key=fn_id, retryable=True)
            if blob is None:
                raise RuntimeError(f"function {fn_id} not found in function table")
            fn = serialization.loads(blob)
            self._fn_cache[fn_id] = fn
        return fn

    # ------------------------------------------------------------------
    # put / get / wait / free (reference core_worker.h:486,662,702)
    # ------------------------------------------------------------------

    def put(self, value: Any, tensor_transport: str = "object") -> ObjectRef:
        with self._task_index_lock:
            self._put_index += 1
            idx = self._put_index
        task_id = self.current_task_id() or TaskID.for_driver(self.current_job_id())
        oid = ObjectID.from_task(task_id, 2**31 + idx)
        if tensor_transport == "device":
            parts = self.device_store.put(oid.hex(), value)
            if parts is not None:
                skeleton, leaves_meta = parts
                self._maybe_eager_export(oid.hex())
                self.memory_store.put(
                    oid,
                    DeviceValue(self.address, oid.hex(), skeleton, leaves_meta),
                )
                return ObjectRef(oid, self.address)
            # no device arrays inside: fall through to the object path
        self.memory_store.put(oid, self._serialize_to_store(oid, value))
        return ObjectRef(oid, self.address)

    def _serialize_to_store(self, oid: ObjectID, value: Any):
        """Serialize a value to its stored form: a PlasmaValue whose frame
        was written through to shm (write-through put: the pickle-5
        buffers are sized first, the segment created at exactly that
        size, then header+meta+buffers land in ONE vectored pwritev — no
        intermediate pack() concatenation, no second shm copy), or an
        in-band frame below the plasma threshold."""
        meta, views = serialization.serialize(value)
        total = serialization.frame_nbytes(meta, views)
        if self._remote_driver or total <= config.max_direct_call_object_size:
            # no local shm on a gateway driver: keep the frame owner-side;
            # consumers fetch via get_object (chunked over the tunnel)
            return serialization.pack_parts(meta, views)
        path = self._write_through_plasma(oid.hex(), meta, views, total)
        return PlasmaValue(path, total, self.node_agent_address, private=True)

    _RECLAIM_MAX = 32
    _RECLAIM_ATTEMPTS = 8

    def _flush_pending_reclaim(self) -> None:
        """Retry deferred reclaims: a segment whose views have died since
        its delete gets recycled (warm pages for the create about to
        happen on the same connection); one whose views persist re-queues
        up to _RECLAIM_ATTEMPTS, then downgrades to a plain delete (the
        pinned mapping keeps its pages either way — the downgrade only
        restores the agent's accounting)."""
        if not self._pending_reclaim:
            return
        with self._pending_reclaim_lock:
            pending = list(self._pending_reclaim)
            self._pending_reclaim.clear()
        for entry in pending:
            oid_hex, path, attempts = entry
            try:
                if self.shm.try_drop(path):
                    self.agent.call_oneway("recycle_object", oid_hex=oid_hex)
                elif attempts + 1 >= self._RECLAIM_ATTEMPTS:
                    # evict the cached mapping too (GC closes it when the
                    # views die) — a cache entry surviving the unlink
                    # would pin the dead pages for the process lifetime
                    self.shm.drop(path)
                    self.agent.call_oneway(
                        "delete_objects", oid_hexes=[oid_hex]
                    )
                else:
                    with self._pending_reclaim_lock:
                        self._pending_reclaim.append(
                            [oid_hex, path, attempts + 1]
                        )
            except RpcError:
                pass

    def _defer_reclaim(self, oid: ObjectID, path: str) -> None:
        overflow = None
        with self._pending_reclaim_lock:
            self._pending_reclaim.append([oid.hex(), path, 0])
            if len(self._pending_reclaim) > self._RECLAIM_MAX:
                overflow = self._pending_reclaim.popleft()
        if overflow is not None:
            self.shm.drop(overflow[1])  # evict cache; GC closes with the views
            try:
                self.agent.call_oneway(
                    "delete_objects", oid_hexes=[overflow[0]]
                )
            except RpcError:
                pass

    def _write_through_plasma(
        self, oid_hex: str, meta, views, total: int
    ) -> str:
        """create_object at the exact frame size, then pwritev the
        scatter-gather pieces straight into the segment. seal rides a
        oneway call: same-host readers only learn the path from the
        marker we store after this returns, and get_meta-based readers
        block on the store's sealed condition, so ordering is safe."""
        self._flush_pending_reclaim()
        path = self.agent.call("create_object", oid_hex=oid_hex, size=total)
        parts = serialization.frame_parts(meta, views)
        fd = os.open(path, os.O_RDWR)
        try:
            pwritev_all(fd, parts)
        finally:
            os.close(fd)
        if serialization.copy_hook is not None:
            serialization.note_copy(total, "put-pwritev")
        self._send_seal(oid_hex)
        return path

    def _send_seal(self, oid_hex: str) -> None:
        """Seal without waiting, but with delivery guaranteed: the frame
        goes out synchronously (in-order with the surrounding create /
        recycle traffic on this connection — the agent's raw handler
        preserves that order), and the ack is checked asynchronously — a
        seal lost to a dropped connection is re-sent with the full retry
        ladder, because an unsealed segment wedges every future reader
        of an object whose put() already reported success."""
        pending = self.agent.call_async("seal_object", oid_hex=oid_hex)

        def _on_done(p, oid_hex=oid_hex):
            if not p.ok:
                self._submit_pool.submit(self._retry_seal, oid_hex)

        pending.add_done_callback(_on_done)

    def _retry_seal(self, oid_hex: str) -> None:
        try:
            self.agent.call("seal_object", oid_hex=oid_hex, retryable=True)
        except RpcError:
            pass  # object deleted meanwhile, or agent truly gone

    @property
    def device_store(self):
        """TPU-RDT device object store (lazy: imports jax machinery only
        when tensor_transport='device' is actually used)."""
        with self._device_store_lock:
            if self._device_store is None:
                from ray_tpu.core.device_objects import DeviceObjectStore

                self._device_store = DeviceObjectStore()
            return self._device_store

    def _fetch_device_value(self, dv) -> Any:
        """Materialize a DeviceValue: zero-copy when this process holds
        the payload; otherwise the holder exports its leaves once into an
        agent shm segment and we mmap it (same host) or stream it over
        the raw-TCP sendfile data plane (cross host), then device_put —
        tensor bytes never ride a pickled RPC reply (VERDICT r4 #3)."""
        import numpy as np

        from ray_tpu.core import device_objects as dev_mod

        if dv.worker_address == self.address:
            return self.device_store.get_value(dv.obj_hex)
        client = self.workers.get(dv.worker_address)
        try:
            meta = client.call(
                "export_device_object", obj_hex=dv.obj_hex, timeout_s=600.0
            )
        except RpcConnectionError as e:
            raise ObjectLostError(
                f"device object {dv.obj_hex[:16]} lost: holder "
                f"{dv.worker_address} unreachable ({e})"
            ) from None
        if meta is None:
            raise ObjectLostError(
                f"device object {dv.obj_hex[:16]} was freed at the holder"
            )
        if (
            meta["agent_addr"] == self.node_agent_address
            and not self._remote_driver
        ):
            # drop any cached mmap of this path first: a retried task can
            # re-export under the same deterministic object id, and a
            # stale mapping of the deleted inode would silently serve the
            # failed attempt's bytes
            self.shm.drop(meta["path"])
            view = self._read_local_segment(meta["path"], meta["size"])
        else:
            view = memoryview(
                self._pull_remote_segment(
                    meta["path"], meta["size"], meta["agent_addr"]
                )
            )
        import jax

        hosts = []
        for (shape, dtype), off in zip(dv.leaves_meta, meta["offsets"]):
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            nbytes = n * np.dtype(dtype).itemsize
            hosts.append(
                np.frombuffer(
                    view[off:off + nbytes], dtype=np.dtype(dtype)
                ).reshape(shape)
            )
        # one batched transfer: jax overlaps the host->device copies
        arrays = jax.device_put(hosts)
        return dev_mod.join_device_value(dv.skeleton, arrays)

    def _store_frame_maybe_plasma(self, oid: ObjectID, frame) -> None:
        """Store an ALREADY-PACKED frame (placement specs, channel relays):
        write-through to shm above the plasma threshold, in-band below."""
        nbytes = len(frame)
        if self._remote_driver or nbytes <= config.max_direct_call_object_size:
            # no local shm on a gateway driver: keep the frame owner-side;
            # consumers fetch via get_object (chunked over the tunnel)
            self.memory_store.put(oid, frame)
            return
        path = self.agent.call("create_object", oid_hex=oid.hex(), size=nbytes)
        fd = os.open(path, os.O_RDWR)
        try:
            pwritev_all(fd, [serialization.as_view(frame)])
        finally:
            os.close(fd)
        if serialization.copy_hook is not None:
            serialization.note_copy(nbytes, "put-pwritev")
        self._send_seal(oid.hex())
        self.memory_store.put(
            oid, PlasmaValue(path, nbytes, self.node_agent_address)
        )

    def get(self, refs: List[ObjectRef], timeout_s: Optional[float] = None) -> List[Any]:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        out = []
        for ref in refs:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            out.append(self._get_one(ref, remaining))
        return out

    def _get_one(self, ref: ObjectRef, timeout_s: Optional[float]) -> Any:
        if self.owns(ref):
            try:
                stored = self.memory_store.get(ref.id, timeout_s)
            except TimeoutError:
                raise GetTimeoutError(
                    f"get() on {ref.id.hex()} timed out after {timeout_s}s"
                ) from None
            try:
                return self._materialize(stored)
            except (ObjectLostError, RpcConnectionError):
                # the value's segment is gone (hosting node died): lineage
                # reconstruction re-executes the creating task. The re-run
                # may overrun a short get timeout — recovery is bounded by
                # the task, not the caller's poll interval (reference
                # recovery is likewise asynchronous w.r.t. the get).
                if not self.reconstruct_object(ref.id):
                    raise
                stored = self.memory_store.get(ref.id, timeout_s)
                return self._materialize(stored)
        client = self.workers.get(ref.owner_address)
        for attempt in range(2):
            try:
                reply = client.call(
                    "get_object", oid_hex=ref.id.hex(), wait_s=timeout_s,
                    requester_agent=(
                        "remote-driver" if self._remote_driver
                        else self.node_agent_address
                    ),
                    timeout_s=(timeout_s + 30.0) if timeout_s is not None else 86400.0,
                )
            except RpcTimeout:
                raise GetTimeoutError(
                    f"get() on {ref.id.hex()} timed out after {timeout_s}s"
                ) from None
            except RpcConnectionError as e:
                raise ObjectLostError(
                    f"owner of {ref.id.hex()} at {ref.owner_address} is "
                    f"unreachable: {e}"
                ) from None
            try:
                return self._materialize_reply(reply)
            except (ObjectLostError, RpcConnectionError):
                # segment pull failed (hosting node died): ask the OWNER to
                # reconstruct from lineage, then re-fetch once. Bounded by
                # the caller's remaining timeout when one was given.
                if attempt > 0:
                    raise
                recon_timeout = 600.0 if timeout_s is None else max(
                    1.0, timeout_s
                )
                try:
                    ok = client.call(
                        "reconstruct_object", oid_hex=ref.id.hex(),
                        timeout_s=recon_timeout,
                    )
                except RpcError:
                    ok = False
                if not ok:
                    raise

    def _materialize(self, stored: Any) -> Any:
        if serialization.is_bytes_like(stored):
            return serialization.unpack(stored)
        if isinstance(stored, PlasmaValue):
            if (
                stored.agent_address != self.node_agent_address
                or self._remote_driver
            ):
                # Owner-side ref to a segment hosted on another node (the
                # producing task ran remotely): pull through that node's
                # agent rather than touching a path that only exists there.
                data = self._pull_remote_segment(
                    stored.path, stored.size, stored.agent_address
                )
                return serialization.unpack(data)
            view = self._read_local_segment(stored.path, stored.size)
            return serialization.unpack(view)
        if isinstance(stored, DeviceValue):
            return self._fetch_device_value(stored)
        if isinstance(stored, TaskError):
            raise stored
        if isinstance(stored, LostValue):
            stored.raise_()
        if isinstance(stored, Exception):
            raise stored
        raise RuntimeError(f"unexpected stored value kind: {type(stored)}")

    def _materialize_reply(self, reply: Tuple[str, Any]) -> Any:
        kind, payload = reply
        if kind == "frame":
            return serialization.unpack(payload)
        if kind == "plasma":
            path, size = payload
            view = self._read_local_segment(path, size)
            return serialization.unpack(view)
        if kind == "remote_plasma":
            # Object lives in another host's shm store: pull it in chunks
            # through that host's node agent (reference C8 object-manager
            # push/pull, object_manager.h:128 — chunked transfer).
            path, size, agent_address = payload
            data = self._pull_remote_segment(path, size, agent_address)
            return serialization.unpack(data)
        if kind == "device":
            addr, skeleton, leaves_meta = payload[:3]
            obj_hex = payload[3]
            return self._fetch_device_value(
                DeviceValue(addr, obj_hex, skeleton, leaves_meta)
            )
        if kind == "error":
            raise payload
        raise RuntimeError(f"unexpected get_object reply kind {kind}")

    def _read_local_segment(self, path: str, size: int) -> memoryview:
        """mmap a same-host segment; if the file is gone the store spilled
        it — ask the agent for the meta (get_meta restores spilled
        segments into shm) and retry. Bounded retries: under heavy
        spill/restore thrash the restored segment can be re-spilled
        before our mmap lands."""
        oid_hex = path.rsplit("_", 1)[-1]
        for _ in range(4):
            try:
                return self.shm.read_view(path, size)
            except FileNotFoundError:
                pass
            meta = self.agent.call(
                "get_object_meta", oid_hex=oid_hex, timeout_s=60.0,
            )
            if meta is None:
                raise ObjectLostError(f"segment {path} is gone from the store")
            path, size = meta
        raise ObjectLostError(
            f"segment {path} kept vanishing (spill/restore thrash)"
        )

    def _pull_remote_segment(
        self, path: str, size: int, agent_address: str
    ) -> memoryview:
        """Chunked pull with a sliding window of chunk RPCs in flight
        (parity: reference PushManager/PullManager pipelining,
        src/ray/object_manager/push_manager.h:28 — one-at-a-time round
        trips made a 1 GiB object ~1,000 serial RPCs). Objects past the
        large-object threshold stream into a disk-backed mmap instead of
        one giant heap bytearray."""
        chunk = int(config.object_transfer_chunk_size)
        window = max(1, int(config.object_transfer_window))
        agent = self.agents.get(agent_address)
        if size >= int(config.object_pull_disk_threshold):
            import tempfile

            f = tempfile.TemporaryFile(prefix="rtpull_")
            f.truncate(max(size, 1))
            import mmap as mmap_mod

            mm = mmap_mod.mmap(f.fileno(), max(size, 1))
            f.close()  # mapping keeps the (anonymous-after-close) file alive
            buf: Any = mm
        else:
            buf = bytearray(size)
        # Data plane first: one raw-TCP request streams the whole segment
        # (agent-side sendfile, native recv pump) — the chunked RPC pull
        # below is the fallback when the agent predates the data port or
        # the stream breaks mid-flight.
        if size > 0 and self._pull_via_data_plane(
            path, size, agent_address, buf
        ):
            return memoryview(buf)
        offsets = list(range(0, size, chunk))
        inflight: "OrderedDict[int, Any]" = OrderedDict()
        next_idx = 0
        done = 0
        while done < len(offsets):
            while next_idx < len(offsets) and len(inflight) < window:
                off = offsets[next_idx]
                n = min(chunk, size - off)
                inflight[off] = agent.call_async(
                    "read_object_chunk", path=path, offset=off, length=n,
                )
                next_idx += 1
            off, pending = next(iter(inflight.items()))
            del inflight[off]
            piece = pending.wait(60.0)
            expected = min(chunk, size - off)
            mv = serialization.as_view(piece) if piece is not None else None
            if mv is None or mv.nbytes != expected:
                # None (file gone) or short (segment truncated/replaced):
                # either way the object is lost. A gap must never be
                # silently zero-filled.
                raise ObjectLostError(
                    f"remote segment {path} vanished during transfer"
                )
            buf[off:off + mv.nbytes] = mv
            if serialization.copy_hook is not None:
                serialization.note_copy(mv.nbytes, "pull-chunk-assemble")
            done += 1
        return memoryview(buf)  # no copy; unpack accepts buffer views

    _DATA_LOST = 0xFFFFFFFFFFFFFFFF

    def _pull_via_data_plane(
        self, path: str, size: int, agent_address: str, buf
    ) -> bool:
        """Stream the whole segment over the agent's data port into
        ``buf``. True on success; False falls back to the chunked RPC
        pull. Raises ObjectLostError when the holder reports the object
        gone (the fallback would fail identically)."""
        import socket
        import struct

        cached = self._data_ports.get(agent_address)
        if cached is not None and time.monotonic() - cached[1] > 60.0:
            cached = None  # stale: agent may have restarted with a new port
        if cached is None:
            try:
                port = int(self.agents.get(agent_address).call(
                    "get_data_port", timeout_s=10.0
                ) or 0)
            except RpcError:
                # transient: fall back THIS pull, ask again next time
                return False
            cached = (port, time.monotonic())
            self._data_ports[agent_address] = cached
        port = cached[0]
        if not port:
            return False
        host = agent_address.rsplit(":", 1)[0]
        from ray_tpu.utils import gateway as gateway_mod

        def _open_data_conn():
            if gateway_mod.gateway_address() is not None:
                # remote-driver mode: the raw data plane tunnels too
                return gateway_mod.open_tunnel(
                    f"{host}:{port}", timeout=5.0
                )
            return socket.create_connection((host, port), timeout=5.0)

        try:
            with _open_data_conn() as s:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # kernel-level receive timeout: the native pump blocks in
                # recv(2) without Python's non-blocking timeout machinery
                s.settimeout(None)
                s.setsockopt(
                    socket.SOL_SOCKET, socket.SO_RCVTIMEO,
                    struct.pack("@ll", 120, 0),
                )
                p = path.encode()
                s.sendall(
                    struct.pack("<I", len(p)) + p
                    + struct.pack("<QQ", 0, size)
                )
                hdr = b""
                while len(hdr) < 8:
                    part = s.recv(8 - len(hdr))
                    if not part:
                        return False
                    hdr += part
                (total,) = struct.unpack("<Q", hdr)
                if total == self._DATA_LOST:
                    raise ObjectLostError(
                        f"remote segment {path} vanished during transfer"
                    )
                if total != size:
                    return False  # truncated view: let the fallback decide
                from ray_tpu import native as native_mod

                lib = native_mod.store_lib()
                if lib is not None:
                    import ctypes

                    cbuf = (ctypes.c_char * size).from_buffer(buf)
                    got = lib.rt_recv_full(
                        s.fileno(), ctypes.addressof(cbuf), size
                    )
                    del cbuf
                else:
                    view = memoryview(buf)
                    got = 0
                    while got < size:
                        n = s.recv_into(view[got:], size - got)
                        if n <= 0:
                            break
                        got += n
                return got == size
        except OSError:
            # broken stream or dead port: drop the cache entry so the next
            # pull re-discovers instead of re-dialing a corpse
            self._data_ports.pop(agent_address, None)
            return False

    def wait(
        self,
        refs: List[ObjectRef],
        num_returns: int = 1,
        timeout_s: Optional[float] = None,
        fetch_local: bool = True,
    ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        local = [r for r in refs if self.owns(r)]
        remote = [r for r in refs if not self.owns(r)]
        if not remote:
            # Fully event-driven: block on the memory store's condition —
            # an arriving object wakes the waiter immediately (reference
            # wait is likewise future-driven, core_worker.h:702; the
            # round-3 20 ms poll tick is gone).
            known = -1
            while True:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                present = self.memory_store.wait_newly_present(
                    [r.id for r in local], known, remaining
                )
                present_set = set(present)
                ready = [r for r in local if r.id in present_set]
                if len(ready) >= num_returns or len(ready) == len(local):
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                known = len(present)
            ready_set = set(ready)
            return ready, [r for r in refs if r not in ready_set]
        return self._wait_mixed(refs, num_returns, deadline)

    def _wait_mixed(self, refs, num_returns, deadline):
        """wait() over refs owned (partly) by other workers: one BLOCKING
        wait_objects RPC per owner (async, completion sets the event) plus
        a memory-store watcher for locally-owned arrivals — event-driven
        end to end, no poll tick."""
        evt = threading.Event()
        self.memory_store.add_watcher(evt)
        inflight: Dict[str, bool] = {}
        replies: Dict[str, set] = {}
        lost: set = set()
        try:
            while True:
                # clear BEFORE recomputing: a completion landing between
                # the scan and the wait must not be lost
                evt.clear()
                ready: List[ObjectRef] = []
                pending: List[ObjectRef] = []
                by_owner: Dict[str, List[ObjectRef]] = {}
                for r in refs:
                    if self.owns(r):
                        if self.memory_store.contains(r.id):
                            ready.append(r)
                        else:
                            pending.append(r)
                    elif r.id.hex() in replies.get(r.owner_address, ()):
                        ready.append(r)
                    elif r.owner_address in lost:
                        # owner unreachable: surfacing the error counts as
                        # ready (get() will raise OwnerDiedError)
                        ready.append(r)
                    else:
                        pending.append(r)
                        by_owner.setdefault(r.owner_address, []).append(r)
                if len(ready) >= num_returns or not pending:
                    return ready, pending
                if deadline is not None and time.monotonic() >= deadline:
                    return ready, pending
                remaining = (
                    30.0 if deadline is None
                    else min(30.0, max(0.05, deadline - time.monotonic()))
                )
                for owner, group in by_owner.items():
                    if inflight.get(owner):
                        continue
                    inflight[owner] = True
                    # the group holds only still-pending oids, none of
                    # which we know to be present — any arrival counts
                    known = 0

                    def _done(p, owner=owner):
                        inflight[owner] = False
                        try:
                            present = p.wait(0)
                            replies.setdefault(owner, set()).update(present)
                        except RpcConnectionError:
                            lost.add(owner)
                        except RpcError:
                            pass
                        evt.set()

                    try:
                        pend = self.workers.get(owner).call_async(
                            "wait_objects",
                            oid_hexes=[r.id.hex() for r in group],
                            known_present=known, wait_s=remaining,
                        )
                        pend.add_done_callback(_done)
                    except RpcConnectionError:
                        lost.add(owner)
                        inflight[owner] = False
                evt.wait(remaining)
        finally:
            self.memory_store.remove_watcher(evt)

    def rpc_wait_objects(
        self, conn, oid_hexes: List[str], known_present: int = -1,
        wait_s: float = 30.0,
    ):
        """Owner side of event-driven wait: block until more of the oids
        are present than the waiter already knows about."""
        oids = [ObjectID.from_hex(h) for h in oid_hexes]
        present = self.memory_store.wait_newly_present(
            oids, known_present, min(wait_s, 120.0)
        )
        return [o.hex() for o in present]

    def free(self, refs: List[ObjectRef]) -> None:
        for ref in refs:
            if self.owns(ref):
                self.delete_owned_object(ref.id)
            else:
                try:
                    self.workers.get(ref.owner_address).call_oneway(
                        "free_object", oid_hex=ref.id.hex()
                    )
                except RpcError:
                    pass

    def delete_owned_object(self, oid: ObjectID) -> None:
        # ref GC runs steadily even when large puts stop, so deferred
        # reclaims can't sit pinned for the worker's lifetime
        self._flush_pending_reclaim()
        stored = self.memory_store.try_get(oid)
        self.memory_store.delete(oid)
        self._drop_lineage_return(oid)
        if isinstance(stored, PlasmaValue):
            # Drop our cached mapping while the file still exists — a
            # mapping pinned past the unlink holds the (dead) pages for
            # the life of the process. try_drop refuses when live views
            # (arrays a get() returned) still reference it.
            local = (
                stored.agent_address == self.node_agent_address
                and not self._remote_driver
            )
            released = self.shm.try_drop(stored.path) if local else True
            try:
                if stored.private and local:
                    if released:
                        # never shared + no live local views: the
                        # segment's pages can be recycled into the next
                        # create. Rides self.agent — the SAME connection
                        # create_object uses — so the raw in-order
                        # handler parks the pages before our next create
                        # asks for them.
                        self.agent.call_oneway(
                            "recycle_object", oid_hex=oid.hex()
                        )
                    else:
                        # views still pin the mapping (the usual case in
                        # `get(put(x))`: the value outlives the ref by a
                        # beat) — defer; the next plasma put retries
                        self._defer_reclaim(oid, stored.path)
                else:
                    if local and not released:
                        # shared segment with live views: evict the cache
                        # entry now (GC closes it with the views) so the
                        # unlinked pages don't stay pinned forever
                        self.shm.drop(stored.path)
                    self.agents.get(stored.agent_address).call_oneway(
                        "delete_objects", oid_hexes=[oid.hex()]
                    )
            except RpcError:
                pass
        elif isinstance(stored, DeviceValue):
            if stored.worker_address == self.address:
                self.device_store.free(stored.obj_hex)
            else:
                try:
                    self.workers.get(stored.worker_address).call_oneway(
                        "free_device_object", obj_hex=stored.obj_hex
                    )
                except RpcError:
                    pass

    def send_add_borrow(
        self,
        owner_address: str,
        oid: ObjectID,
        register_token: Optional[str] = None,
        consume_token: Optional[str] = None,
    ) -> None:
        try:
            self.workers.get(owner_address).call_oneway(
                "add_borrow", oid_hex=oid.hex(),
                register_token=register_token, consume_token=consume_token,
            )
        except RpcError:
            pass

    def send_release_borrow(
        self, owner_address: str, oid: ObjectID, n: int = 1
    ) -> None:
        try:
            self.workers.get(owner_address).call_oneway(
                "release_borrow", oid_hex=oid.hex(), n=n
            )
        except RpcError:
            pass

    def _pack_task_args(self, payload, task_hex: str) -> bytes:
        """Pack task args, taking a pendency borrow on every ObjectRef
        serialized inside — held until the task reaches a terminal state
        (_release_arg_pins). Unlike the in-flight serialization pin
        (consumed by the first deserialization), the pendency borrow
        survives long lease-queue waits AND retries. Reference parity:
        borrow reports keep task-arg refs alive for the task's whole
        pendency (reference_counter.h:44)."""
        tr = self.reference_tracker
        tr.begin_capture()
        try:
            frame = serialization.pack(payload)
        finally:
            pins = tr.end_capture()
        if pins:
            self._arg_pins[task_hex] = pins
            for addr, oid, owned in pins:
                if owned:
                    tr.add_task_borrow(oid)
                else:
                    self.send_add_borrow(addr, oid)
        # big args frames ride push_task as a raw trailing wire segment
        # instead of being re-pickled in-band per hop
        return serialization.maybe_frame(frame)

    def _release_arg_pins(self, task_hex: str) -> None:
        """Task reached a terminal state: drop its args' pendency borrows."""
        pins = self._arg_pins.pop(task_hex, None)
        if not pins:
            return
        tr = self.reference_tracker
        for addr, oid, owned in pins:
            if owned:
                tr.owner_release_borrow(oid)
            else:
                self.send_release_borrow(addr, oid)

    # ------------------------------------------------------------------
    # normal task submission (reference normal_task_submitter.h:124)
    # ------------------------------------------------------------------

    def submit_task(self, fn_id, fn_name, args, kwargs, options: TaskOptions):
        task_id = self._next_task_id()
        if options.num_returns == -1:  # streaming generator
            from ray_tpu.core.object_ref import ObjectRefGenerator

            refs = [ObjectRefGenerator(task_id, self)]
        else:
            refs = [
                ObjectRef(ObjectID.from_task(task_id, i), self.address)
                for i in range(options.num_returns)
            ]
        # Anything that can raise resolves BEFORE packing the args: packing
        # takes pendency borrows that only terminal task states release.
        strategy = self._resolve_strategy(options.scheduling_strategy)
        runtime_env = runtime_env_mod.prepare(options.runtime_env, self.control)
        spec = TaskSpec(
            task_id=task_id,
            fn_id=fn_id,
            fn_name=fn_name,
            args_frame=self._pack_task_args((args, kwargs), task_id.hex()),
            num_returns=options.num_returns,
            owner_address=self.address,
            resources=options.resource_demand(default_cpus=1.0),
            max_retries=(
                options.max_retries
                if options.max_retries is not None
                else config.task_max_retries
            ),
            retry_exceptions=options.retry_exceptions,
            name=options.name or fn_name,
            runtime_env=runtime_env,
            tensor_transport=options.tensor_transport or "object",
        )
        with self._lineage_lock:
            self._lineage[task_id.hex()] = [spec, strategy, options.num_returns]
            self._lineage_bytes += len(spec.args_frame)
            while len(self._lineage) > int(config.lineage_max_entries) or (
                self._lineage_bytes > int(config.lineage_max_bytes)
                and len(self._lineage) > 1
            ):
                _, dropped = self._lineage.popitem(last=False)
                self._lineage_bytes -= len(dropped[0].args_frame)
        if tracing.ENABLED:
            self._append_task_event(tracing.lifecycle_event(
                tracing.SUBMITTED, task_id.hex(), spec.name, self.address,
            ))
        pending_deps = self._pending_arg_deps(args, kwargs)
        if pending_deps:
            # The task must not compete for a worker lease until every
            # top-level ObjectRef arg is available — an executor blocking
            # on an upstream producer while HOLDING a leased CPU starves
            # the producers themselves (shuffle reduce-before-map
            # deadlock). Reference: local_dependency_resolver.h.
            self.dep_resolver.add(
                pending_deps,
                lambda: self._enqueue_normal_task(spec, strategy),
            )
        else:
            self._enqueue_normal_task(spec, strategy)
        return refs

    def _enqueue_normal_task(self, spec: TaskSpec, strategy) -> None:
        """Route a ready-to-run task to its scheduling key's submitter
        (lease cache). Keys split on anything that changes which worker
        may run the task: resource shape, placement strategy, runtime
        env (reference SchedulingKey, normal_task_submitter.h:52)."""
        key = (
            tuple(sorted(spec.resources.items())),
            repr(strategy),
            repr(spec.runtime_env),
        )
        while True:
            with self._task_submitters_lock:
                sub = self._task_submitters.get(key)
                if sub is None:
                    sub = _NormalTaskSubmitter(
                        self, spec.resources, strategy, spec.runtime_env
                    )
                    self._task_submitters[key] = sub
                    if self._submitter_janitor is None:
                        self._submitter_janitor = threading.Thread(
                            target=self._janitor_loop,
                            name="task-submit-janitor", daemon=True,
                        )
                        self._submitter_janitor.start()
            if sub.submit(spec):
                return
            # lost the race with the janitor's disposal sweep: drop the
            # dead entry and mint a fresh submitter
            with self._task_submitters_lock:
                if self._task_submitters.get(key) is sub:
                    del self._task_submitters[key]

    def _janitor_loop(self) -> None:
        """ONE maintenance thread for every scheduling key's submitter
        (a thread per key would leak: each PG strategy mints a key):
        stall scaling, idle-lease keepalive reaping, and disposal of
        long-empty submitters; releases all cached leases at shutdown."""
        while not self._shutdown.is_set():
            time.sleep(0.05)
            with self._task_submitters_lock:
                items = list(self._task_submitters.items())
            dead = [key for key, sub in items if sub.maintain_tick()]
            if dead:
                with self._task_submitters_lock:
                    for key in dead:
                        sub = self._task_submitters.get(key)
                        # try_dispose re-verifies emptiness under the
                        # submitter lock and marks it disposed, so a
                        # submit racing this sweep either lands before
                        # (keeps the submitter) or sees _disposed and
                        # re-registers a fresh one
                        if sub is not None and sub.try_dispose():
                            del self._task_submitters[key]
        with self._task_submitters_lock:
            subs = list(self._task_submitters.values())
        for sub in subs:
            sub.release_all()

    def _pending_arg_deps(self, args, kwargs) -> List[ObjectRef]:
        """Top-level ObjectRef args not yet known to be available (Ray
        semantics: only top-level refs are task dependencies; nested refs
        pass through un-awaited)."""
        deps = [a for a in args if isinstance(a, ObjectRef)]
        deps.extend(v for v in kwargs.values() if isinstance(v, ObjectRef))
        pending, seen = [], set()
        for r in deps:
            if r.id in seen:
                continue
            seen.add(r.id)
            if self.owns(r):
                if not self.memory_store.contains(r.id):
                    pending.append(r)
            else:
                pending.append(r)  # resolver confirms with the owner
        return pending

    @property
    def dep_resolver(self) -> "_DependencyResolver":
        with self._dep_resolver_lock:
            if self._dep_resolver is None:
                self._dep_resolver = _DependencyResolver(self)
            return self._dep_resolver

    def _drop_lineage_return(self, oid: ObjectID) -> None:
        """An owned object was deleted: its task's lineage entry loses a
        live return; at zero the entry (and its retained args) drops."""
        task_hex = oid.task_id().hex()
        with self._lineage_lock:
            entry = self._lineage.get(task_hex)
            if entry is None:
                return
            entry[2] -= 1
            if entry[2] <= 0:
                self._lineage.pop(task_hex, None)
                self._lineage_bytes -= len(entry[0].args_frame)

    def _object_really_lost(self, oid: ObjectID) -> bool:
        """Distinguish a dead segment from a transient blip: if the
        hosting agent still answers and holds the object, do NOT
        re-execute (a reconstruction over a live value would race the
        existing segment)."""
        stored = self.memory_store.try_get(oid)
        if isinstance(stored, DeviceValue):
            try:
                return not self.workers.get(stored.worker_address).call(
                    "device_object_contains", obj_hex=stored.obj_hex,
                    timeout_s=5.0,
                )
            except RpcError:
                return True  # holder unreachable: device payload is gone
        if not isinstance(stored, PlasmaValue):
            return not os_mod.is_missing(stored) and isinstance(
                stored, LostValue
            )
        try:
            return not self.agents.get(stored.agent_address).call(
                "object_contains", oid_hex=oid.hex(), timeout_s=5.0,
            )
        except RpcError:
            return True  # agent unreachable: treat as lost

    def reconstruct_object(self, oid: ObjectID) -> bool:
        """Re-execute the task that created oid (lineage reconstruction,
        reference object_recovery_manager.h:26). Single-flight per task;
        returns True if the value is available again (either a
        re-execution ran, one was joined, or the loss turned out to be a
        transient failure and the value is intact)."""
        task_hex = oid.task_id().hex()
        with self._lineage_lock:
            entry = self._lineage.get(task_hex)
            if entry is None:
                return False
            event = self._reconstructing.get(task_hex)
            if event is None:
                event = threading.Event()
                self._reconstructing[task_hex] = event
                leader = True
            else:
                leader = False
        if not leader:
            event.wait(timeout=600.0)
            return True
        try:
            if not self._object_really_lost(oid):
                return True
            spec, strategy = entry[0], entry[1]
            logger.warning(
                "reconstructing lost object %s by re-executing task %s",
                oid.hex()[:16], spec.name,
            )
            self._submit_normal_task(spec, strategy)
            return True
        finally:
            event.set()
            with self._lineage_lock:
                self._reconstructing.pop(task_hex, None)

    def rpc_reconstruct_object(self, conn, oid_hex: str):
        """Borrower-triggered reconstruction: a remote reader failed to
        pull our object's segment (hosting node died)."""
        return self.reconstruct_object(ObjectID.from_hex(oid_hex))

    def _resolve_strategy(self, strategy):
        """Convert API strategy objects into the wire dict form."""
        from ray_tpu.core.placement import PlacementGroupSchedulingStrategy
        from ray_tpu.core.api import NodeAffinitySchedulingStrategy

        if strategy is None or strategy == "DEFAULT":
            return None
        if isinstance(strategy, str):
            return strategy
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            return {
                "type": "placement_group",
                "pg_id": strategy.placement_group.id_hex,
                "bundle_index": strategy.placement_group_bundle_index,
            }
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            return {
                "type": "node_affinity",
                "node_id": strategy.node_id,
                "soft": strategy.soft,
            }
        if isinstance(strategy, dict):
            return strategy
        raise TypeError(f"unsupported scheduling strategy {strategy!r}")

    def _submit_normal_task(self, spec: TaskSpec, strategy) -> None:
        attempts = spec.max_retries + 1
        last_error: Optional[Exception] = None
        for attempt in range(attempts):
            if spec.task_id.hex() in self._cancelled_tasks:
                err = TaskCancelledError(f"task {spec.name} was cancelled")
                self._store_error_returns(spec, err)
                return
            try:
                self._run_task_on_lease(spec, strategy)
                return
            except (RpcConnectionError, RpcTimeout, WorkerCrashedError) as e:
                last_error = e
                logger.warning(
                    "task %s attempt %d/%d failed: %s",
                    spec.name, attempt + 1, attempts, e,
                )
                if isinstance(e, RpcConnectionError):
                    # The failure may be our own node agent dying (a driver
                    # outlives its node, unlike workers): re-attach to a
                    # surviving agent before retrying.
                    self._maybe_reattach_agent()
                continue
            except TaskError as e:
                last_error = e
                if spec.retry_exceptions and attempt + 1 < attempts:
                    continue
                break
            except Exception as e:  # noqa: BLE001 — store scheduling errors
                last_error = e
                break
        err = last_error
        if not isinstance(err, TaskError):
            err = TaskError(
                f"task {spec.name} failed after {attempts} attempts: {last_error}",
            )
        self._store_error_returns(spec, err)

    def _maybe_reattach_agent(self) -> None:
        """Driver-only: if our node agent is unreachable, re-attach to a
        surviving alive node (reference parity gap P14: the remote driver
        must not die with the node it happened to pick at init)."""
        if self.mode != "driver":
            return
        with self._reattach_lock:
            try:
                self.agent.call("store_usage", timeout_s=3.0)
                return  # agent alive; failure was elsewhere
            except RpcConnectionError:
                pass
            except RpcError:
                return  # slow, not dead
            try:
                view = self.control.call("get_cluster_view", timeout_s=10.0)
            except RpcError:
                return
            for nid, node in view.items():
                addr = node["address"]
                if addr == self.node_agent_address:
                    continue
                probe = RpcClient(addr, name="driver->agent")
                try:
                    probe.call("store_usage", timeout_s=3.0)
                except RpcError:
                    probe.close()
                    continue
                logger.warning(
                    "driver re-attaching from dead agent %s to %s",
                    self.node_agent_address, addr,
                )
                old = self.agent
                self.agent = probe
                self.node_agent_address = addr
                self.node_id_hex = nid
                try:
                    old.close()
                except Exception:  # noqa: BLE001
                    pass
                return

    def _run_task_on_lease(self, spec: TaskSpec, strategy) -> None:
        bundle = None
        if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
            bundle = (strategy["pg_id"], strategy.get("bundle_index"))
        agent = self.agent
        hops = 0
        while True:
            lease = agent.call(
                "lease_worker",
                resources=spec.resources,
                bundle=bundle,
                strategy=strategy,
                wait_s=30.0,
                timeout_s=45.0,
                runtime_env=spec.runtime_env,
            )
            if lease.get("granted"):
                break
            spill = lease.get("spillback")
            if spill:
                hops += 1
                if hops > 16:
                    raise TaskError(f"task {spec.name}: too many spillback hops")
                agent = self.agents.get(spill)
                continue
            if lease.get("error") == "lease timeout":
                # Stay queued (reference behavior: leases wait). The agent
                # answers instantly for pending PGs, so back off briefly to
                # avoid hammering it and the control store in a tight loop.
                time.sleep(0.2)
                continue
            raise TaskError(
                f"task {spec.name} unschedulable: {lease.get('error')} "
                f"(resources={spec.resources})"
            )
        worker_addr = lease["worker_address"]
        lease_id = lease["lease_id"]
        if spec.task_id.hex() in self._cancelled_tasks:
            # cancelled while waiting for the lease
            try:
                agent.call_oneway("release_worker", lease_id=lease_id, kill=False)
            except RpcError:
                pass
            err = TaskCancelledError(f"task {spec.name} was cancelled")
            self._store_error_returns(spec, err)
            return
        kill = False
        self._inflight_push[spec.task_id.hex()] = worker_addr
        try:
            client = self.workers.get(worker_addr)
            # Task duration is unbounded: effectively no RPC timeout here;
            # worker death is detected by connection loss instead.
            reply = client.call("push_task", spec=spec, timeout_s=86400.0 * 30)
            self._store_task_reply(spec, reply)
        except (RpcConnectionError, RpcTimeout):
            if spec.tensor_transport == "device":
                # The executor may have finished and parked device-resident
                # returns before the reply was lost; a retry lands on a new
                # worker, so free any HBM the (possibly still-alive) first
                # executor pinned for this task. Best-effort on the
                # EXISTING connection only — reconnecting to a dead worker
                # would stall the retry path for rpc_connect_timeout_s.
                try:
                    c = self.workers.get(worker_addr)
                    if c._sock is not None:
                        for i in range(max(spec.num_returns, 0)):
                            c.call_oneway(
                                "free_device_object",
                                obj_hex=ObjectID.from_task(
                                    spec.task_id, i
                                ).hex(),
                            )
                except RpcError:
                    pass
            self.workers.drop(worker_addr)
            kill = True
            raise WorkerCrashedError(
                f"worker {worker_addr} died while executing {spec.name}"
            ) from None
        finally:
            self._inflight_push.pop(spec.task_id.hex(), None)
            try:
                agent.call_oneway("release_worker", lease_id=lease_id, kill=kill)
            except RpcError:
                pass

    def _stream_done_oid(self, task_id: TaskID) -> ObjectID:
        return ObjectID.from_task(task_id, self._STREAM_DONE_INDEX)

    def _drop_stale_stream_items(self, spec: TaskSpec, count: int) -> None:
        """A retried streaming task can leave items from a longer failed
        attempt at indices >= the final count; the generator (correctly)
        never yields them, so free them here lest they leak. Items are
        pushed in order, so stale ones sit contiguously from `count`."""
        idx = count
        while idx < count + 100000:  # safety bound
            oid = ObjectID.from_task(spec.task_id, idx)
            stored = self.memory_store.try_get(oid)
            if os_mod.is_missing(stored):
                break
            self.memory_store.delete(oid)
            if isinstance(stored, PlasmaValue):
                try:
                    self.agents.get(stored.agent_address).call_oneway(
                        "delete_objects", oid_hexes=[oid.hex()]
                    )
                except RpcError:
                    pass
            idx += 1

    def _store_error_returns(self, spec: TaskSpec, err: Exception) -> None:
        """Fail every return slot. Streaming tasks (num_returns == -1)
        have no fixed slots: the error lands in the done-marker, which the
        ObjectRefGenerator raises when it reaches it."""
        self._release_arg_pins(spec.task_id.hex())
        if spec.num_returns == -1:
            self.memory_store.put(self._stream_done_oid(spec.task_id), err)
            return
        for i in range(spec.num_returns):
            self.memory_store.put(ObjectID.from_task(spec.task_id, i), err)

    def rpc_stream_item(self, conn, task_id_hex: str, index: int, payload):
        """Owner side: one streamed generator item landed (in-order
        oneway pushes from the executor)."""
        oid = ObjectID.from_task(TaskID.from_hex(task_id_hex), index)
        kind, data = payload
        if kind == "frame":
            self.memory_store.put(oid, data)
        else:
            path, size, agent_addr = data
            self.memory_store.put(oid, PlasmaValue(path, size, agent_addr))
        return True

    def _store_task_reply(self, spec: TaskSpec, reply: Dict[str, Any]) -> None:
        if reply.get("status") == "interrupted":
            # a stray cancel interrupt hit this (innocent) task: surface
            # it in the type each retry ladder classifies as retryable
            # (the lease-cache path also special-cases it pre-store)
            if spec.actor_id is not None:
                raise ActorUnavailableError(
                    f"actor task {spec.name} caught a stray cancel "
                    "interrupt"
                )
            raise WorkerCrashedError(
                f"task {spec.name} caught a stray cancel interrupt"
            )
        if reply["status"] != "error" or not spec.retry_exceptions:
            # terminal (the retry_exceptions error path re-raises to the
            # retry loop: the task is still pending, so its args keep
            # their pendency borrows for the next attempt)
            self._release_arg_pins(spec.task_id.hex())
        if reply["status"] == "ok" and spec.num_returns == -1:
            # streaming: items arrived via rpc_stream_item pushes (possibly
            # still in flight on another connection — the generator waits
            # for item i even after seeing the count); store the count
            count = reply["returns"][0][1]
            self.memory_store.put(self._stream_done_oid(spec.task_id), count)
            self._drop_stale_stream_items(spec, int(count))
            return
        if reply["status"] == "ok":
            for oid_hex, (kind, payload) in reply["returns"]:
                oid = ObjectID.from_hex(oid_hex)
                if kind == "frame":
                    self.memory_store.put(oid, payload)
                elif kind == "plasma":
                    path, size, agent_addr = payload
                    self.memory_store.put(oid, PlasmaValue(path, size, agent_addr))
                elif kind == "device":
                    addr, skeleton, leaves_meta = payload
                    self.memory_store.put(
                        oid, DeviceValue(addr, oid_hex, skeleton, leaves_meta)
                    )
                if self.reference_tracker.maybe_delete_unreferenced(oid):
                    # every ref (and borrow) died while the task was running
                    self.delete_owned_object(oid)
        elif reply["status"] == "cancelled":
            err = TaskCancelledError(f"task {spec.name} was cancelled")
            self._store_error_returns(spec, err)
        else:
            error: TaskError = reply["error"]
            if spec.retry_exceptions:
                raise error
            self._store_error_returns(spec, error)

    # ------------------------------------------------------------------
    # actor submission (reference actor_task_submitter.h)
    # ------------------------------------------------------------------

    def create_actor(self, class_id, class_blob, class_name, init_args, init_kwargs,
                     actor_options) -> str:
        actor_id = ActorID.of(self.current_job_id()).hex()
        self.register_function(class_id, class_blob, class_name)
        # resolve fallible inputs before packing (packing takes pendency
        # borrows that need a terminal event to release)
        strategy = self._resolve_strategy(
            actor_options.get("scheduling_strategy")
        )
        runtime_env = runtime_env_mod.prepare(
            actor_options.get("runtime_env"), self.control
        )
        spec = {
            "actor_id": actor_id,
            "job_id": self.current_job_id().hex(),
            "class_id": class_id,
            "class_name": class_name,
            # actor-creation args can wait arbitrarily long in PG queues;
            # the pendency borrows are released when the creator first
            # observes the actor ALIVE or DEAD (_resolve_actor_address) —
            # an actor the creator never interacts with keeps them until
            # process exit, which is the semantics of holding the handle
            "init_args_frame": self._pack_task_args(
                (init_args, init_kwargs), f"actor_init_{actor_id}"
            ),
            "resources": actor_options.get("resources", {}),
            "name": actor_options.get("name"),
            "namespace": actor_options.get("namespace", "default"),
            "lifetime": actor_options.get("lifetime"),
            "max_restarts": actor_options.get("max_restarts", 0),
            "max_task_retries": actor_options.get("max_task_retries", 0),
            "max_concurrency": actor_options.get("max_concurrency", 1),
            "concurrency_groups": actor_options.get("concurrency_groups"),
            "method_groups": actor_options.get("method_groups"),
            "method_names": actor_options.get("method_names", []),
            "scheduling_strategy": strategy,
            "runtime_env": runtime_env,
            "owner_address": self.address,
        }
        if int(spec["max_restarts"] or 0) != 0:
            # a restart re-deserializes init_args_frame: the pendency
            # borrows must survive until the actor is PERMANENTLY dead
            self._restartable_actor_inits.add(actor_id)
        try:
            batcher = self._actor_batcher()
            if batcher is not None:
                batcher.enqueue_register(spec)
                if spec.get("name"):
                    # named creation keeps synchronous semantics: a name
                    # conflict must raise HERE, not at first use
                    batcher.wait_registered(actor_id)
            else:
                self.control.call("register_actor", spec=spec, retryable=True)
        except BaseException:
            self._restartable_actor_inits.discard(actor_id)
            self._release_arg_pins(f"actor_init_{actor_id}")
            raise
        return actor_id

    def _actor_batcher(self) -> Optional["_ActorLifecycleBatcher"]:
        """The lifecycle batcher, or None when batching is off
        (actor_batch_flush_ms=0 — the legacy one-RPC-per-actor path)."""
        if float(config.actor_batch_flush_ms) <= 0:
            return None
        b = self._lifecycle_batcher
        if b is None:
            with self._lifecycle_batcher_lock:
                b = self._lifecycle_batcher
                if b is None:
                    b = self._lifecycle_batcher = _ActorLifecycleBatcher(self)
        return b

    def _await_actor_registered(self, actor_id: str,
                                timeout_s: float = 60.0) -> None:
        """Surface a batched registration's per-record error (no-op for
        ids registered synchronously or long since flushed)."""
        b = self._lifecycle_batcher
        if b is None:
            return
        try:
            b.wait_registered(actor_id, timeout_s)
        except BaseException:
            self._restartable_actor_inits.discard(actor_id)
            self._release_arg_pins(f"actor_init_{actor_id}")
            raise

    def _actor_sender(self, actor_id: str) -> "_ActorSender":
        with self._actor_senders_lock:
            sender = self._actor_senders.get(actor_id)
            if sender is None:
                sender = _ActorSender(self, actor_id)
                self._actor_senders[actor_id] = sender
        return sender

    def _resolve_actor_address(self, actor_id: str, timeout_s: float = 60.0) -> str:
        """Block until the actor is ALIVE, up to timeout_s total (pending
        creation / restart / resource queuing can legitimately take long —
        reference callers block on the GCS actor table the same way, but
        the timeout bounds the WHOLE wait, not each control-store call)."""
        if actor_id in self._locally_killed:
            # killed from this process: the kill may still be riding the
            # lifecycle batch, but its outcome is already decided
            raise ActorDiedError(f"actor {actor_id} was killed")
        addr = self._actor_addr_cache.get(actor_id)
        if addr:
            return addr
        self._await_actor_registered(actor_id, timeout_s=timeout_s)
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = max(0.05, deadline - time.monotonic())
            info = self.control.call(
                "wait_actor_alive", actor_id=actor_id, wait_s=remaining,
                timeout_s=remaining + 30.0, retryable=True,
            )
            if info is None:
                self._restartable_actor_inits.discard(actor_id)
                self._release_arg_pins(f"actor_init_{actor_id}")
                raise ActorDiedError(f"actor {actor_id} does not exist")
            if info["state"] == "DEAD":
                self._restartable_actor_inits.discard(actor_id)
                self._release_arg_pins(f"actor_init_{actor_id}")
                raise ActorDiedError(
                    f"actor {actor_id} is dead: {info.get('death_cause')}"
                )
            if info["state"] == "ALIVE" and info.get("worker_address"):
                self._actor_addr_cache[actor_id] = info["worker_address"]
                if actor_id not in self._restartable_actor_inits:
                    # creation args were consumed by the actor start and a
                    # non-restartable actor never re-reads them
                    self._release_arg_pins(f"actor_init_{actor_id}")
                return info["worker_address"]
            if self._shutdown.is_set() or time.monotonic() >= deadline:
                raise ActorUnavailableError(f"actor {actor_id} is {info['state']}")
            time.sleep(0.05)

    def _actor_max_task_retries(self, actor_id: str) -> int:
        n = self._actor_retry_cache.get(actor_id)
        if n is not None:
            return n
        try:
            # a batched registration may still be in flight; get_actor_info
            # on an unknown actor would silently report 0 retries
            self._await_actor_registered(actor_id, timeout_s=30.0)
        except Exception:  # noqa: BLE001 — submission surfaces the error
            pass
        try:
            info = self.control.call("get_actor_info", actor_id=actor_id)
            n = int((info or {}).get("max_task_retries") or 0)
        except RpcError:
            n = 0
        self._actor_retry_cache[actor_id] = n
        return n

    def submit_actor_task(self, actor_id: str, method_name: str, args, kwargs,
                          num_returns: int = 1,
                          tensor_transport: str = "object") -> List[ObjectRef]:
        task_id = TaskID.for_actor_task(ActorID.from_hex(actor_id))
        if num_returns == -1:  # streaming actor method (generator)
            from ray_tpu.core.object_ref import ObjectRefGenerator

            refs: List[Any] = [ObjectRefGenerator(task_id, self)]
        else:
            refs = [
                ObjectRef(ObjectID.from_task(task_id, i), self.address)
                for i in range(num_returns)
            ]
        spec = TaskSpec(
            task_id=task_id,
            fn_id="",
            fn_name=method_name,
            args_frame=self._pack_task_args((args, kwargs), task_id.hex()),
            num_returns=num_returns,
            owner_address=self.address,
            resources={},
            # opt-in at-least-once for actor methods (reference
            # task_manager.h max_task_retries): connection-loss failures
            # are re-submitted to the restarted actor up to this many times
            max_retries=self._actor_max_task_retries(actor_id),
            actor_id=actor_id,
            method_name=method_name,
            name=f"{actor_id[:8]}.{method_name}",
            tensor_transport=tensor_transport,
        )
        if tracing.ENABLED:
            self._append_task_event(tracing.lifecycle_event(
                tracing.SUBMITTED, task_id.hex(), spec.name, self.address,
            ))
        pending_deps = self._pending_arg_deps(args, kwargs)
        if pending_deps:
            # awaited by the sender thread just before the send — ordered
            # per-caller, so later calls queue behind as Ray's sequence
            # numbers would
            self._pending_task_deps[task_id.hex()] = pending_deps
        self._actor_sender(actor_id).submit(spec)
        return refs

    def _store_actor_task_failure(self, spec: TaskSpec, e: Exception) -> None:
        self._release_arg_pins(spec.task_id.hex())
        if not isinstance(e, (TaskError, ActorDiedError, ActorUnavailableError)):
            e = TaskError(f"actor task {spec.name} failed: {e}", traceback.format_exc())
        if spec.num_returns == -1:
            # streaming: the error marker rides the done-slot, raised by
            # the ObjectRefGenerator after the produced prefix is consumed
            self.memory_store.put(self._stream_done_oid(spec.task_id), e)
            return
        for i in range(spec.num_returns):
            self.memory_store.put(ObjectID.from_task(spec.task_id, i), e)

    def _actor_connection_lost(self, spec: TaskSpec) -> Exception:
        """Classify a connection loss for an in-flight actor task.

        At-most-once semantics (reference default max_task_retries=0): the
        task may or may not have executed, so it is NEVER silently resent —
        the caller gets ActorDiedError (permanent) or ActorUnavailableError
        (actor restarting; new calls will reach the restarted actor)."""
        self._actor_addr_cache.pop(spec.actor_id, None)
        try:
            info = self.control.call(
                "get_actor_info", actor_id=spec.actor_id, retryable=True
            )
        except RpcError:
            info = None
        if info is None or info["state"] == "DEAD":
            self._restartable_actor_inits.discard(spec.actor_id)
            self._release_arg_pins(f"actor_init_{spec.actor_id}")
            return ActorDiedError(
                f"actor {spec.actor_id[:8]} died: "
                f"{info.get('death_cause') if info else 'unknown'}"
            )
        return ActorUnavailableError(
            f"actor {spec.actor_id[:8]} is {info['state']}; in-flight call "
            f"{spec.name} failed (not retried: at-most-once semantics)"
        )

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        if no_restart:
            # record BEFORE the (possibly batched) RPC: a submit racing
            # the flush must observe the kill deterministically
            self._locally_killed.add(actor_id)
        batcher = self._actor_batcher()
        if batcher is not None:
            batcher.enqueue_kill(actor_id, no_restart)
        else:
            self.control.call(
                "kill_actor", actor_id=actor_id, no_restart=no_restart
            )
        self._actor_addr_cache.pop(actor_id, None)
        if no_restart:
            self._restartable_actor_inits.discard(actor_id)
            self._release_arg_pins(f"actor_init_{actor_id}")

    def drop_actor_handle(self, actor_id: str) -> None:
        """Owner handle GC. Routed through the lifecycle batcher so a
        drop can never overtake its actor's still-queued registration at
        the store (an unknown-actor drop is a silent no-op — the actor
        would register right after and leak)."""
        batcher = self._actor_batcher()
        if batcher is not None:
            batcher.enqueue_drop(actor_id)
        else:
            self.control.call_oneway(
                "actor_handle_dropped", actor_id=actor_id
            )

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> None:
        """Cancel (reference core_worker.h Cancel): tasks not yet
        dispatched are dropped owner-side; tasks already pushed get a
        cancel RPC. A RUNNING task is interrupted executor-side:
        force=False raises KeyboardInterrupt in its thread (the
        reference's non-force semantics), force=True kills the executing
        worker process outright (a task stuck in C code or refusing the
        interrupt still dies; the owner's retry ladder sees the
        cancellation and stores TaskCancelledError instead of retrying)."""
        task_hex = ref.task_id().hex()
        self._cancelled_tasks.add(task_hex)
        worker_addr = self._inflight_push.get(task_hex)
        if worker_addr:
            try:
                self.workers.get(worker_addr).call_oneway(
                    "cancel_task", task_id_hex=task_hex, force=force
                )
            except RpcError:
                pass

    # ------------------------------------------------------------------
    # execution side: worker service RPCs
    # ------------------------------------------------------------------

    def rpc_push_task(self, conn, spec: TaskSpec):
        return self._execute_spec(spec)

    def rpc_push_tasks(self, conn, specs: List[TaskSpec]):
        """Batched normal-task push: the owner coalesces queued short
        tasks bound for one leased worker into a single RPC, amortizing
        the ~100us frame roundtrip across the batch (the lease cache only
        batches when the measured service latency is sub-5ms, so a slow
        task never delays unrelated replies)."""
        return [self._execute_spec(s) for s in specs]

    def _raw_actor_task(self, conn, req_id, args, kwargs) -> None:
        spec: TaskSpec = kwargs.get("spec") or args[0]
        rt = self._actor_runtime
        if rt is None:
            RpcServer.reply(
                conn, req_id, False,
                RemoteError("this worker hosts no actor", ""),
            )
            return
        rt.queue_for(spec.method_name).put((conn, req_id, spec))

    def _actor_loop(self, q: "queue.Queue") -> None:
        rt = self._actor_runtime
        while not self._shutdown.is_set():
            try:
                conn, req_id, spec = q.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                if rt.is_async:
                    # Async actor (any `async def` method makes the WHOLE
                    # actor async, like the reference): every method runs
                    # on the one event loop — coroutines overlap at
                    # awaits, sync methods run to completion on the loop
                    # thread — so actor state is single-threaded and
                    # scheduling order follows submission order. The
                    # executor thread frees immediately; the reply is sent
                    # from a pool thread on completion.
                    self._execute_async_actor_task(conn, req_id, spec)
                    continue
                incremented = False
                try:
                    with rt.running_lock:
                        rt.running += 1
                        incremented = True
                    reply = self._execute_spec(spec)
                except KeyboardInterrupt:
                    # stray cancel interrupt delivered outside
                    # _execute_spec's try block: this persistent executor
                    # thread must survive
                    reply = {"status": "interrupted"}
                finally:
                    if incremented:
                        with rt.running_lock:
                            rt.running -= 1
                try:
                    RpcServer.reply(conn, req_id, True, reply)
                except KeyboardInterrupt:
                    # mid-send interrupt may have written a partial frame:
                    # resending would desync the multiplexed stream — drop
                    # the connection instead (the caller's conn-loss path
                    # classifies and retries)
                    conn.alive = False
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
            except KeyboardInterrupt:
                # stray interrupt outside the guarded regions: a just-
                # dequeued item or a computed-but-unsent reply may be
                # lost, so DROP the connection — the caller's conn-loss
                # path retries per its policy instead of hanging forever
                # — and keep this persistent thread alive
                try:
                    conn.alive = False
                    conn.sock.close()
                except (OSError, NameError, AttributeError):
                    pass  # interrupt landed before a conn was dequeued
                continue

    def _execute_async_actor_task(self, conn, req_id, spec: TaskSpec) -> None:
        import asyncio
        import inspect

        rt = self._actor_runtime
        _t0 = time.time()
        try:
            target = getattr(rt.instance, spec.method_name)
            args, kwargs = serialization.unpack(spec.args_frame)
            args = [self._resolve_arg(a) for a in args]
            kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
            if inspect.iscoroutinefunction(target):
                coro = target(*args, **kwargs)
            else:
                async def _sync_on_loop(t=target, a=args, kw=kwargs):
                    return t(*a, **kw)

                coro = _sync_on_loop()
        except Exception as e:  # noqa: BLE001
            RpcServer.reply(conn, req_id, True, {
                "status": "error",
                "error": TaskError(
                    f"{type(e).__name__}: {e}", traceback.format_exc(),
                    cause=e,
                ),
            })
            return
        with rt.running_lock:
            rt.running += 1
        fut = asyncio.run_coroutine_threadsafe(coro, rt.ensure_loop())

        def _finish(f):
            with rt.running_lock:
                rt.running -= 1
            try:
                result = f.result()
                reply = {
                    "status": "ok",
                    "returns": self._package_returns(spec, result),
                }
            except Exception as e:  # noqa: BLE001
                reply = {
                    "status": "error",
                    "error": TaskError(
                        f"{type(e).__name__}: {e}", traceback.format_exc(),
                        cause=e,
                    ),
                }
            if tracing.ENABLED:
                self._append_task_event({
                    "name": spec.name or spec.method_name,
                    "task_id": spec.task_id.hex(),
                    "actor_id": spec.actor_id,
                    "ts_us": int(_t0 * 1e6),
                    "dur_us": int((time.time() - _t0) * 1e6),
                    "worker": self.address,
                    "pid": os.getpid(),
                })
            RpcServer.reply(conn, req_id, True, reply)

        # the reply path serializes results and makes plasma RPCs — hand
        # it to a pool thread so the event loop never blocks on it
        fut.add_done_callback(
            lambda f: self._submit_pool.submit(_finish, f)
        )

    def rpc_actor_direct_call(self, conn, target: str, args=(), kwargs=None):
        """Latency-optimized call into the hosted actor instance for the
        serve data plane: the proxy invokes the replica's request method
        DIRECTLY on this server's cached dispatcher thread — no TaskSpec,
        no return-object registration, no executor-queue hop, no owner-
        side memory-store put. Replies ride the same multi-segment frames
        as every RPC, so a wrapped (serialization.Frame) response body
        ≥32 KiB travels as a raw out-of-band segment.

        The actor's max_concurrency bound still applies: direct calls
        gate on rt.direct_sem (same limit as the executor pool), so a
        max_concurrency=1 deployment's callable never runs concurrently
        on this path either — excess direct calls block their dispatcher
        thread until a slot frees. Only methods designed for direct
        dispatch (serve replicas' handle_request_direct, which do their
        own ongoing accounting) should be targeted. The in-flight count
        still reflects in actor_queue_stats via rt.running so the pow-2
        router and the autoscaler keep seeing direct load.

        Returns ("ok", result) or ("no_actor", reason) — the marker, not
        an error, so the router can fall back to the ordinary actor-task
        path without burning its retry ladder."""
        rt = self._actor_runtime
        if rt is None:
            return ("no_actor", "this worker hosts no actor")
        fn = getattr(rt.instance, target, None)
        if fn is None:
            return ("no_actor", f"actor has no method {target!r}")
        with rt.direct_sem:  # the actor's max_concurrency bound
            with rt.running_lock:
                rt.running += 1
            try:
                return ("ok", fn(*args, **(kwargs or {})))
            finally:
                with rt.running_lock:
                    rt.running -= 1

    def rpc_actor_queue_stats(self, conn):
        """Queue depth + in-flight count for the hosted actor, served by
        the RPC layer (NOT the actor's execution queue) so probes answer
        instantly even when every actor thread is busy — the reference
        replica's out-of-band queue-length probe."""
        rt = self._actor_runtime
        if rt is None:
            return None
        with rt.running_lock:
            running = rt.running
        out = {"queued": rt.total_queued(), "running": running}
        # serve model multiplexing: piggyback the replica's loaded model
        # ids on the out-of-band probe (no extra RPC, and no import cost
        # unless the process actually uses @serve.multiplexed)
        import sys as _sys

        mux = _sys.modules.get("ray_tpu.serve.multiplex")
        if mux is not None:
            try:
                out["multiplexed_model_ids"] = mux.loaded_model_ids()
            except Exception:  # noqa: BLE001 — stats must never fail
                pass
        return out

    def rpc_create_actor(self, conn, spec: Dict[str, Any]):
        """Returns {"ok": True} or {"ok": False, "error": TaskError}.

        Application-level __init__ failures travel as data, NOT as RPC
        errors — the control store must distinguish "constructor raised"
        (actor is DEAD, tell the user why) from "transport failed" (retry
        on another worker)."""
        try:
            # Actor runtime env applies for the worker's whole life — the
            # process is dedicated to this actor (reference: worker-pool
            # processes are keyed by runtime-env hash).
            runtime_env_mod.apply_permanent(
                spec.get("runtime_env"), self.control
            )
            cls = self.load_function(spec["class_id"])
            args, kwargs = serialization.unpack(spec["init_args_frame"])
            args = [self._resolve_arg(a) for a in args]
            kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
            self._current_ctx.job_id = JobID.from_hex(spec["job_id"])
            instance = cls(*args, **kwargs)
        except Exception as e:  # noqa: BLE001
            return {
                "ok": False,
                "error": TaskError(
                    f"actor {spec['class_name']}.__init__ failed: {e}",
                    traceback.format_exc(),
                ),
            }
        rt = _ActorRuntime(
            spec["actor_id"], instance, int(spec.get("max_concurrency", 1)),
            concurrency_groups=spec.get("concurrency_groups"),
            method_groups=spec.get("method_groups"),
        )
        self._actor_runtime = rt
        for i in range(rt.max_concurrency):
            t = threading.Thread(
                target=self._actor_loop, args=(rt.queue,),
                name=f"actor-exec-{i}", daemon=True,
            )
            t.start()
            rt.threads.append(t)
        for group, limit in rt.group_limits.items():
            for i in range(max(1, int(limit))):
                t = threading.Thread(
                    target=self._actor_loop, args=(rt.group_queues[group],),
                    name=f"actor-{group}-{i}", daemon=True,
                )
                t.start()
                rt.threads.append(t)
        return {"ok": True}

    def _execute_spec(self, spec: TaskSpec) -> Dict[str, Any]:
        if spec.task_id.hex() in self._cancelled_tasks:
            return {"status": "cancelled"}
        self._current_ctx.task_id = spec.task_id
        self._current_ctx.job_id = spec.task_id.job_id()
        self._running_tasks[spec.task_id.hex()] = {
            "name": spec.name, "tid": threading.get_ident(),
            "t0": time.monotonic(),
        }
        _t0 = time.time()
        try:
            if spec.actor_id is not None:
                rt = self._actor_runtime
                if spec.method_name == "__rt_dag_exec_loop__":
                    # compiled-graph exec loop (ray_tpu/dag.py): a system
                    # task that parks on this actor until DAG teardown
                    import functools

                    from ray_tpu import dag as dag_mod

                    target = functools.partial(
                        dag_mod._actor_exec_loop, rt.instance
                    )
                elif spec.method_name == "__rt_pipe_exec_loop__":
                    # compiled-pipeline stage loop (parallel/pipeline.py):
                    # parks on this stage actor until pipeline teardown
                    import functools

                    from ray_tpu.parallel import pipeline as pipeline_mod

                    target = functools.partial(
                        pipeline_mod._stage_exec_loop, rt.instance
                    )
                else:
                    target = getattr(rt.instance, spec.method_name, None)
                if target is None:
                    raise AttributeError(
                        f"actor has no method {spec.method_name!r}"
                    )
            else:
                target = self.load_function(spec.fn_id)
            args, kwargs = serialization.unpack(spec.args_frame)
            args = [self._resolve_arg(a) for a in args]
            kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
            if spec.runtime_env and spec.runtime_env == getattr(
                self, "boot_env_spec", None
            ):
                # env-keyed pool hit: this worker BOOTED inside the env
                # (worker_main applied it permanently) — skip per-task
                # setup entirely (reference: env-hash worker binning)
                result = target(*args, **kwargs)
            else:
                with runtime_env_mod.apply(spec.runtime_env, self.control):
                    result = target(*args, **kwargs)
            returns = self._package_returns(spec, result)
            return {"status": "ok", "returns": returns}
        except KeyboardInterrupt:
            if spec.task_id.hex() in self._cancelled_tasks:
                return {"status": "cancelled"}
            # a cancel aimed at a task that finished in the delivery
            # window landed here instead: this task is innocent — report
            # "interrupted" so the owner retries it rather than failing
            return {"status": "interrupted"}
        except TaskError as e:
            return {"status": "error", "error": e}
        except Exception as e:  # noqa: BLE001 — forwarded to the owner
            return {
                "status": "error",
                "error": TaskError(
                    f"{type(e).__name__}: {e}", traceback.format_exc(), cause=e
                ),
            }
        finally:
            self._running_tasks.pop(spec.task_id.hex(), None)
            self._current_ctx.task_id = None
            if tracing.ENABLED:
                self._append_task_event({
                    "name": spec.name or spec.fn_name,
                    "task_id": spec.task_id.hex(),
                    "actor_id": spec.actor_id,
                    "ts_us": int(_t0 * 1e6),
                    "dur_us": int((time.time() - _t0) * 1e6),
                    "worker": self.address,
                    "pid": os.getpid(),
                })

    def _append_task_event(self, evt: Dict[str, Any]) -> None:
        """Append to the bounded event ring, counting silent evictions —
        a full ring drops the OLDEST event, so long runs would otherwise
        truncate their timelines undetectably."""
        ring = self._task_events
        if len(ring) == ring.maxlen:
            self._task_events_dropped += 1
            if core_metrics.ENABLED:
                core_metrics.task_events_dropped.inc()
        ring.append(evt)

    def rpc_get_task_events(self, conn, clear: bool = False,
                            types: Optional[List[str]] = None):
        """Drain/peek this worker's event ring. ``types`` filters
        server-side by the events' "type" key — the metrics-history
        sampler polls request spans every second, and shipping a full
        10k-event ring per worker per tick (mostly lifecycle/exec
        events under actor-heavy load) would make the sampler the
        biggest RPC client in the cluster."""
        # list() first: one atomic C-level copy under the GIL — a python
        # -level comprehension over the live deque would race concurrent
        # appends (RuntimeError: deque mutated during iteration)
        events = list(self._task_events)
        if types is not None:
            want = set(types)
            events = [e for e in events if e.get("type") in want]
        dropped = self._task_events_dropped
        if clear:
            # window semantics: clearing starts a fresh window, so the
            # drop count must restart with it
            self._task_events.clear()
            self._task_events_dropped = 0
        return {"events": events, "dropped": dropped}

    def rpc_get_metrics(self, conn):
        from ray_tpu.utils import metrics as metrics_mod

        return {
            "token": metrics_mod.PROCESS_TOKEN,
            "metrics": metrics_mod.snapshot_all(),
        }

    def rpc_profile(self, conn, duration_s: float = 5.0,
                    hz: float = 99.0):
        """Sample this worker's threads for ``duration_s`` at ``hz``
        (both clamped inside profiler.capture)."""
        return profiler.capture(duration_s=duration_s, hz=hz)

    def rpc_stack_dump(self, conn):
        """All-thread stacks from this live worker (hang forensics)."""
        return forensics.all_thread_stacks()

    def rpc_borrow_stats(self, conn):
        """Owner-side reference state for `state.objects()` / `rt memory`
        (leaked-borrow triage: an object held only by an old in-flight
        pin is a borrow that never completed)."""
        return self.reference_tracker.stats()

    def _resolve_arg(self, value: Any) -> Any:
        if isinstance(value, ObjectRef):
            return self._get_one(value, timeout_s=None)
        return value

    _STREAM_DONE_INDEX = 2**31 - 1  # sentinel return slot: item count

    def _package_returns(self, spec: TaskSpec, result: Any) -> List[Tuple[str, Any]]:
        if spec.num_returns == -1:
            return self._stream_returns(spec, result)
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} declared num_returns={spec.num_returns} "
                    f"but returned {len(values)} values"
                )
        returns = []
        for i, value in enumerate(values):
            oid = ObjectID.from_task(spec.task_id, i)
            if spec.tensor_transport == "device":
                parts = self.device_store.put(oid.hex(), value)
                if parts is not None:
                    skeleton, leaves_meta = parts
                    self._maybe_eager_export(oid.hex())
                    returns.append((
                        oid.hex(),
                        ("device", (self.address, skeleton, leaves_meta)),
                    ))
                    continue
                # no device arrays in the value: ordinary object path
            meta, views = serialization.serialize(value)
            total = serialization.frame_nbytes(meta, views)
            if total > config.max_direct_call_object_size:
                path = self._write_through_plasma(oid.hex(), meta, views, total)
                returns.append(
                    (oid.hex(), ("plasma", (path, total, self.node_agent_address)))
                )
            else:
                # big frames ride the reply as a raw trailing wire segment
                # (multi-segment RPC) instead of an in-band re-pickle
                returns.append((oid.hex(), ("frame", serialization.maybe_frame(
                    serialization.pack_parts(meta, views)))))
        return returns

    def _stream_returns(self, spec: TaskSpec, result: Any) -> List[Tuple[str, Any]]:
        """num_returns="streaming": push each yielded value to the OWNER
        as it is produced (reference: streaming generators,
        task_manager's dynamic returns) — the consumer's
        ObjectRefGenerator sees item i long before the task finishes.
        Items ride in-order oneway RPCs; big items go through plasma and
        only their marker travels."""
        owner = self.workers.get(spec.owner_address)
        count = 0
        for value in result:
            oid = ObjectID.from_task(spec.task_id, count)
            meta, views = serialization.serialize(value)
            total = serialization.frame_nbytes(meta, views)
            if total > config.max_direct_call_object_size:
                path = self._write_through_plasma(oid.hex(), meta, views, total)
                payload = ("plasma", (path, total, self.node_agent_address))
            else:
                payload = ("frame", serialization.maybe_frame(
                    serialization.pack_parts(meta, views)))
            owner.call_oneway(
                "stream_item", task_id_hex=spec.task_id.hex(),
                index=count, payload=payload,
            )
            count += 1
        # the count marker travels on the ordinary reply path
        return [("__stream_count__", count)]

    # -- object service (owner side) --

    def rpc_get_object(
        self,
        conn,
        oid_hex: str,
        wait_s: Optional[float] = None,
        requester_agent: Optional[str] = None,
    ):
        oid = ObjectID.from_hex(oid_hex)
        try:
            stored = self.memory_store.get(oid, wait_s)
        except TimeoutError:
            return ("error", GetTimeoutError(f"object {oid_hex} not ready"))
        if serialization.is_bytes_like(stored):
            # big frames ride the reply as a raw wire segment — never
            # re-pickled in-band
            if not isinstance(stored, serialization.Frame):
                stored = serialization.maybe_frame(stored)
            return ("frame", stored)
        if isinstance(stored, PlasmaValue):
            # the path escapes to another process: the segment is shared
            # from here on and must never be page-recycled. Clear the
            # bit FIRST, then re-check liveness: delete_owned_object
            # removes the marker from the store BEFORE it reads
            # `private`, so either our re-check sees the deletion (reply
            # error, no path escapes) or the deleter sees private=False
            # (no recycle) — a concurrently-deleted segment can never be
            # both handed out and page-recycled.
            stored.private = False
            if os_mod.is_missing(self.memory_store.try_get(oid)):
                return ("error", ObjectLostError(
                    f"object {oid_hex} was freed during get"
                ))
            if (
                requester_agent is not None
                and requester_agent != stored.agent_address
            ):
                # Requester is on a different host: the shm path is useless
                # to it. Hand back the hosting agent's address so the
                # requester pulls the segment in chunks from that agent.
                return (
                    "remote_plasma",
                    (stored.path, stored.size, stored.agent_address),
                )
            return ("plasma", (stored.path, stored.size))
        if isinstance(stored, DeviceValue):
            return (
                "device",
                (stored.worker_address, stored.skeleton, stored.leaves_meta,
                 stored.obj_hex),
            )
        if isinstance(stored, LostValue):
            return ("error", ObjectLostError(stored.message))
        if isinstance(stored, Exception):
            return ("error", stored)
        return ("error", RuntimeError(f"bad stored kind {type(stored)}"))

    def rpc_peek_object(self, conn, oid_hex: str):
        return self.memory_store.contains(ObjectID.from_hex(oid_hex))

    def rpc_peek_objects(self, conn, oid_hexes: List[str]):
        return [
            self.memory_store.contains(ObjectID.from_hex(h)) for h in oid_hexes
        ]

    def rpc_free_object(self, conn, oid_hex: str):
        self.delete_owned_object(ObjectID.from_hex(oid_hex))
        return True

    def _maybe_eager_export(self, obj_hex: str) -> None:
        """Kick the shm export in the background the moment a device
        value is parked (task return / put): the D2H + segment write
        overlaps the consumer task's submit/schedule latency instead of
        sitting on its first-get critical path — the producer-side half
        of hiding transfer behind execution (arxiv 1909.09756). The
        export is single-flight and cached, so the consumer's
        ``export_device_object`` RPC finds it done (or joins it
        mid-flight); a value freed before any consumer reads it deletes
        the eager segment through the normal free path. RT_RDT_EAGER_
        EXPORT=0 restores lazy first-get exports (saves the wasted work
        when consumers are usually in-process)."""
        if not config.rdt_eager_export:
            return
        if not self._eager_export_sem.acquire(blocking=False):
            return  # throttled: this object exports lazily on first get

        def _run():
            try:
                self._export_device_segment(obj_hex)
            except Exception:  # noqa: BLE001 — consumer path will retry
                pass
            finally:
                self._eager_export_sem.release()

        threading.Thread(
            target=_run, daemon=True, name="rt-rdt-eager-export"
        ).start()

    def rpc_export_device_object(self, conn, obj_hex: str):
        """Export a device object's leaf buffers ONCE into a shm segment
        hosted by this node's agent, and hand consumers (path, size,
        offsets): a same-host consumer mmaps it zero-copy; a cross-host
        consumer streams it over the raw-TCP sendfile data plane. This
        replaces the pickled control-RPC reply as the bulk path — the
        host bounce the reference's RDT transports exist to avoid
        (reference nixl_tensor_transport.py:1 role; VERDICT r4 fix #3).
        Returns None when the object is not (or no longer) held here."""
        if self._device_store is None or not self._device_store.contains(obj_hex):
            return None
        try:
            return self._export_device_segment(obj_hex)
        except KeyError:
            return None

    def _export_device_segment(self, obj_hex: str) -> Dict[str, Any]:
        import numpy as np

        # per-object single-flight: the exports lock only guards the
        # cache dict — holding it across the D2H copy + agent RPCs would
        # serialize unrelated exports and block rpc_free_device_object
        while True:
            with self._device_exports_lock:
                entry = self._device_exports.get(obj_hex)
                if isinstance(entry, dict):
                    return entry
                if entry is None:
                    inflight = threading.Event()
                    self._device_exports[obj_hex] = inflight
                    break
            entry.wait(timeout=300.0)  # another thread is exporting
        try:
            meta = self._build_device_export(obj_hex)
            with self._device_exports_lock:
                if self._device_exports.get(obj_hex) is inflight:
                    self._device_exports[obj_hex] = meta
                else:
                    # freed mid-export: don't leak the fresh segment
                    try:
                        self.agent.call_oneway(
                            "delete_objects", oid_hexes=[obj_hex]
                        )
                    except RpcError:
                        pass
            return meta
        except BaseException:
            with self._device_exports_lock:
                if self._device_exports.get(obj_hex) is inflight:
                    del self._device_exports[obj_hex]
            raise
        finally:
            inflight.set()

    def _build_device_export(self, obj_hex: str) -> Dict[str, Any]:
        from ray_tpu.core import device_objects as dev_mod

        arrays = self.device_store.arrays(obj_hex)
        # layout from avals only — nothing materializes until the
        # overlapped writer stages it chunk by chunk
        offsets, total = dev_mod.plan_export_layout(arrays)
        try:
            path = self.agent.call(
                "create_object", oid_hex=obj_hex, size=total
            )
        except RemoteError:
            # a stale segment from a freed predecessor: replace it
            self.agent.call("delete_objects", oid_hexes=[obj_hex])
            path = self.agent.call(
                "create_object", oid_hex=obj_hex, size=total
            )
        # pwrite, not mmap: writing fresh tmpfs pages through a
        # mapping pays a page-fault per 4K page (~3x slower than the
        # kernel's bulk allocate+copy in write(2)). The writer double-
        # buffers: D2H of chunk k overlaps the pwrite of chunk k-1
        # (device_objects.write_arrays_overlapped).
        fd = os.open(path, os.O_RDWR)
        try:
            dev_mod.write_arrays_overlapped(fd, arrays, offsets)
        finally:
            os.close(fd)
        # oneway: consumers read the bytes by path, not through the
        # agent, so nothing downstream waits on the seal bookkeeping
        # (same-connection ordering still lands it before any later
        # call from this worker)
        self.agent.call_oneway("seal_object", oid_hex=obj_hex)
        return {
            "path": path,
            "size": total,
            "offsets": offsets,
            "agent_addr": self.node_agent_address,
        }

    def rpc_device_object_contains(self, conn, obj_hex: str):
        return (
            self._device_store is not None
            and self._device_store.contains(obj_hex)
        )

    def rpc_free_device_object(self, conn, obj_hex: str):
        if self._device_store is not None:
            self._device_store.free(obj_hex)
        with self._device_exports_lock:
            exported = self._device_exports.pop(obj_hex, None)
        if exported is not None:
            try:
                self.agent.call_oneway("delete_objects", oid_hexes=[obj_hex])
            except RpcError:
                pass
        return True

    def rpc_device_store_stats(self, conn):
        if self._device_store is None:
            return {"device_objects": 0, "device_bytes": 0}
        return self._device_store.stats()

    def rpc_add_borrow(
        self, conn, oid_hex: str, register_token=None, consume_token=None
    ):
        self.reference_tracker.owner_add_borrow(
            ObjectID.from_hex(oid_hex),
            register_token=register_token,
            consume_token=consume_token,
        )
        return True

    def rpc_release_borrow(self, conn, oid_hex: str, n: int = 1):
        self.reference_tracker.owner_release_borrow(ObjectID.from_hex(oid_hex), n=n)
        return True

    def rpc_cancel_task(self, conn, task_id_hex: str, force: bool = False):
        self._cancelled_tasks.add(task_id_hex)
        running = self._running_tasks.get(task_id_hex)
        if running is None:
            return True
        if force:
            # force-cancel semantics (reference: force=True kills the
            # worker): the task may be wedged in native code where no
            # Python exception can land. The owner detects the connection
            # loss; the cancelled task stores TaskCancelledError and any
            # batch peers retry elsewhere.
            logger.warning(
                "force-cancel: killing worker over task %s", task_id_hex[:16]
            )
            os.kill(os.getpid(), 9)
            return True  # unreachable
        tid = running.get("tid")
        if tid is not None:
            import ctypes

            # re-verify IDENTITY at the last instant: _execute_spec pops
            # the entry in its finally before the thread can exit, so an
            # entry that is still present with the same tid cannot belong
            # to a reused thread ident
            current = self._running_tasks.get(task_id_hex)
            if current is None or current.get("tid") != tid:
                return True
            # the reference raises KeyboardInterrupt in the executing
            # thread for non-force cancellation of a running task
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(KeyboardInterrupt)
            )
        return True

    def rpc_coll_deliver(self, conn, group: str, token: str, tag: str,
                         payload=None, poison: Optional[str] = None):
        """Host-collective ring transport (collective/p2p.py): peer ranks
        dial this worker DIRECTLY and deliver chunk payloads into the
        target group's mailbox — the worker↔worker hop the p2p
        collectives ride, with ndarray payloads arriving as raw
        out-of-band multiseg segments (recv_into preallocated buffers),
        never through the control store. Idempotent per (group
        incarnation token, tag), so senders retry freely across
        connection drops; a stale token (destroyed/re-initialized group)
        drops the delivery. ``poison`` carries ring failure propagation
        instead of a payload."""
        from ray_tpu.collective import p2p

        return p2p.deliver(group, token, tag, payload, poison=poison)

    def rpc_chan_push(self, conn, chan_id: str, seq: int, payload,
                      slots: int = 1):
        """Cross-host channel delivery (core/channels.py RpcChannel):
        the compiled-pipeline stage-boundary hop for stages that do not
        share a host. The payload arrives Frame-wrapped when ≥ the
        multiseg floor — raw out-of-band segments on the wire, never an
        in-band re-pickle. Idempotent per (chan_id, seq); a full mailbox
        bounces with ``full`` (the writer's retry loop is the
        backpressure)."""
        from ray_tpu.core import channels as channels_mod

        return channels_mod.rpc_channel_deliver(chan_id, seq, payload, slots)

    def rpc_ping(self, conn):
        return {"worker_id": self.worker_id.hex(), "mode": self.mode,
                "actor": self.current_actor_id()}

    def rpc_exit_worker(self, conn):
        def _die():
            time.sleep(0.05)
            os._exit(0)

        threading.Thread(target=_die, daemon=True).start()
        return True


class _DependencyResolver:
    """Owner-side task dependency resolution (reference
    local_dependency_resolver.h): a normal task whose top-level ObjectRef
    args are not yet available must not compete for a worker lease —
    executors would hold leased CPUs while blocked fetching upstream
    outputs, starving the very producer tasks they wait on (observed as
    the shuffle reduce-before-map lease deadlock).

    Event-driven: locally-owned arrivals wake the loop through a
    memory-store watcher; deps owned by other workers resolve through
    async wait_objects RPCs to their owners (completion re-wakes the
    loop). An unreachable owner marks its deps resolved — the executor
    surfaces OwnerDiedError at arg fetch, which is the reference's
    error-propagation path too."""

    def __init__(self, worker: CoreWorker):
        self.worker = worker
        self._lock = threading.Lock()
        # entries: [pending deps list, ready callback]
        self._entries: List[List] = []
        self._remote_present: set = set()  # oid hexes confirmed at owners
        self._owners_lost: set = set()
        self._inflight: Dict[str, bool] = {}
        self._evt = threading.Event()
        worker.memory_store.add_watcher(self._evt)
        self._thread = threading.Thread(
            target=self._loop, name="dep-resolver", daemon=True
        )
        self._thread.start()

    def add(self, deps: List[ObjectRef], ready_cb) -> None:
        with self._lock:
            self._entries.append([list(deps), ready_cb])
        self._evt.set()

    def _dep_ready(self, r: ObjectRef) -> bool:
        w = self.worker
        if w.owns(r):
            return w.memory_store.contains(r.id)
        return (
            r.id.hex() in self._remote_present
            or r.owner_address in self._owners_lost
        )

    def _loop(self) -> None:
        w = self.worker
        while not w._shutdown.is_set():
            self._evt.wait(1.0)
            self._evt.clear()
            ready_cbs: List = []
            by_owner: Dict[str, set] = {}
            with self._lock:
                still: List[List] = []
                for deps, cb in self._entries:
                    remaining = [r for r in deps if not self._dep_ready(r)]
                    if remaining:
                        still.append([remaining, cb])
                        for r in remaining:
                            if not w.owns(r):
                                by_owner.setdefault(
                                    r.owner_address, set()
                                ).add(r.id.hex())
                    else:
                        ready_cbs.append(cb)
                self._entries = still
                # prune confirmations no longer referenced by any entry
                if self._remote_present:
                    referenced: set = set()
                    for hexes in by_owner.values():
                        referenced |= hexes
                    self._remote_present &= referenced
            for owner, hexes in by_owner.items():
                if self._inflight.get(owner) or owner in self._owners_lost:
                    continue
                self._inflight[owner] = True

                def _done(p, owner=owner):
                    self._inflight[owner] = False
                    try:
                        present = p.wait(0)
                        with self._lock:
                            self._remote_present.update(present)
                    except RpcConnectionError:
                        self._owners_lost.add(owner)
                    except RpcError:
                        pass  # transient: next pass re-issues
                    self._evt.set()

                try:
                    pend = w.workers.get(owner).call_async(
                        "wait_objects", oid_hexes=sorted(hexes),
                        known_present=0, wait_s=30.0,
                    )
                    pend.add_done_callback(_done)
                except RpcError:
                    self._owners_lost.add(owner)
                    self._inflight[owner] = False
                    self._evt.set()
            for cb in ready_cbs:
                try:
                    cb()
                except Exception:  # noqa: BLE001
                    logger.exception("dependency-ready callback failed")


class _ActorLifecycleBatcher:
    """Client-side actor lifecycle coalescing (ISSUE 14).

    ``create_actor`` / ``kill_actor`` enqueue and return immediately; one
    flusher thread ships a single ``register_actors`` / ``kill_actors``
    RPC per flush window (``actor_batch_flush_ms``), amortizing one RPC
    round trip + one scheduler wakeup over the whole batch — the
    10k-actor launch storm a Podracer-style job produces in one loop.

    Semantics preserved:
      * named creations wait synchronously (``wait_registered``) so a
        name conflict still raises at ``.remote()`` time;
      * per-record results — one bad spec fails only its own creation,
        surfaced at ``wait_registered`` (first address resolution);
      * intra-batch ordering — kills/drops for actors registered in the
        SAME window land after the register RPC, kills for other actors
        land before it (a named replacement may be waiting on the old
        holder's death);
      * retried batches are safe: the store treats duplicate register
        (same actor_id) and duplicate kill as idempotent ok.
    """

    def __init__(self, worker: "CoreWorker"):
        self._worker = worker
        self._cv = threading.Condition(threading.Lock())
        self._pending_reg: Dict[str, Dict[str, Any]] = {}
        self._pending_kill: List[Tuple[str, bool]] = []
        self._pending_drop: List[str] = []
        self._inflight: set = set()  # actor_ids in a register RPC
        self._errors: Dict[str, str] = {}  # actor_id -> per-record error
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    def enqueue_register(self, spec: Dict[str, Any]) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("worker is shutting down")
            self._pending_reg[spec["actor_id"]] = spec
            self._ensure_thread_locked()
            self._cv.notify_all()

    def enqueue_kill(self, actor_id: str, no_restart: bool) -> None:
        with self._cv:
            if self._closed:
                return
            self._pending_kill.append((actor_id, no_restart))
            self._ensure_thread_locked()
            self._cv.notify_all()

    def enqueue_drop(self, actor_id: str) -> None:
        with self._cv:
            if self._closed:
                return
            self._pending_drop.append(actor_id)
            self._ensure_thread_locked()
            self._cv.notify_all()

    def wait_registered(self, actor_id: str, timeout_s: float = 60.0) -> None:
        """Block until the batch carrying this registration was acked,
        re-raising its per-record error. Ids this batcher never saw (or
        that already flushed clean) return immediately."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while actor_id in self._pending_reg or actor_id in self._inflight:
                if time.monotonic() >= deadline:
                    raise ActorUnavailableError(
                        f"actor {actor_id} registration not acked in {timeout_s}s"
                    )
                self._cv.notify_all()  # wake the flusher: cut the window
                self._cv.wait(0.5)
            err = self._errors.pop(actor_id, None)
        if err is not None:
            raise ValueError(f"actor registration failed: {err}")

    def close(self, timeout_s: float = 5.0) -> None:
        """Flush everything still queued and stop the thread."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    def _ensure_thread_locked(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="actor-lifecycle-batch", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not (self._pending_reg or self._pending_kill
                           or self._pending_drop or self._closed):
                    self._cv.wait(0.5)
                if self._closed and not (
                    self._pending_reg or self._pending_kill or self._pending_drop
                ):
                    return
            flush_s = float(config.actor_batch_flush_ms) / 1000.0
            if flush_s > 0 and not self._closed:
                time.sleep(flush_s)  # accumulation window
            with self._cv:
                regs = list(self._pending_reg.values())
                self._pending_reg.clear()
                kills, self._pending_kill = self._pending_kill, []
                drops, self._pending_drop = self._pending_drop, []
                self._inflight.update(s["actor_id"] for s in regs)
            try:
                self._flush(regs, kills, drops)
            except Exception:  # noqa: BLE001 — keep the flusher alive
                logger.exception("actor lifecycle flush failed")
                with self._cv:
                    for s in regs:
                        self._inflight.discard(s["actor_id"])
                        self._errors.setdefault(
                            s["actor_id"], "lifecycle flush failed"
                        )
            with self._cv:
                self._cv.notify_all()

    def _flush(self, regs: List[Dict[str, Any]],
               kills: List[Tuple[str, bool]], drops: List[str]) -> None:
        reg_ids = {s["actor_id"] for s in regs}
        self._send_kills([k for k in kills if k[0] not in reg_ids])
        if regs:
            try:
                res = self._worker.control.call(
                    "register_actors", specs=regs, retryable=True,
                    timeout_s=120.0,
                )
            except BaseException as e:  # noqa: BLE001 — whole batch failed
                res = [
                    {"actor_id": s["actor_id"], "ok": False,
                     "error": f"{type(e).__name__}: {e}"}
                    for s in regs
                ]
            with self._cv:
                for r in res:
                    if not r.get("ok"):
                        self._errors[r.get("actor_id")] = (
                            r.get("error") or "registration failed"
                        )
                for s in regs:
                    self._inflight.discard(s["actor_id"])
                self._cv.notify_all()
        self._send_kills([k for k in kills if k[0] in reg_ids])
        for actor_id in drops:
            try:
                self._worker.control.call_oneway(
                    "actor_handle_dropped", actor_id=actor_id
                )
            except RpcError:
                pass

    def _send_kills(self, kills: List[Tuple[str, bool]]) -> None:
        for flag in (True, False):
            ids = [aid for aid, nr in kills if nr is flag]
            if ids:
                try:
                    self._worker.control.call(
                        "kill_actors", actor_ids=ids, no_restart=flag,
                        retryable=True, timeout_s=120.0,
                    )
                except RpcError as e:
                    logger.warning(
                        "batched kill of %d actor(s) failed: %s", len(ids), e
                    )


class _ActorSender:
    """Caller-side ordered, pipelined actor task submission.

    Parity: ActorTaskSubmitter's per-caller sequence ordering (reference
    src/ray/core_worker/task_submission/actor_task_submitter.h). One sender
    thread serializes the sends (so frames hit the actor's socket in
    submission order — the server's raw handler enqueues them in arrival
    order), while a waiter thread collects replies, keeping many calls in
    flight. After a connection break the affected call falls back to the
    synchronous resend path and strict ordering is relaxed for the tail
    (the reference similarly re-queues on actor restart).
    """

    def __init__(self, worker: CoreWorker, actor_id: str):
        self.worker = worker
        self.actor_id = actor_id
        self.specs: "queue.Queue" = queue.Queue()
        # (pending, spec) pairs whose reply/failure has LANDED: populated
        # by per-call done-callbacks, so replies are processed in
        # COMPLETION order — a long-running call (an actor method that
        # blocks for minutes) must not head-of-line block the replies of
        # later calls that already finished on other executor threads.
        self.completed: "queue.Queue" = queue.Queue()
        self.attempts: Dict[str, int] = {}  # task_id hex -> retries used
        self._sender = threading.Thread(
            target=self._send_loop, name=f"actor-send-{actor_id[:8]}", daemon=True
        )
        self._waiter = threading.Thread(
            target=self._wait_loop, name=f"actor-wait-{actor_id[:8]}", daemon=True
        )
        self._sender.start()
        self._waiter.start()

    def submit(self, spec: TaskSpec) -> None:
        self.specs.put(spec)

    def _maybe_retry(self, spec: TaskSpec, err: Exception) -> bool:
        """Actor max_task_retries: re-queue a call that failed on
        connection loss while the actor restarts (at-least-once — the
        method may have executed; only opt-in via max_task_retries,
        reference task_manager.h:175). Permanent death never retries."""
        if spec.max_retries <= 0 or not isinstance(err, ActorUnavailableError):
            return False
        attempts = self.attempts.get(spec.task_id.hex(), 0)
        if attempts >= spec.max_retries:
            self.attempts.pop(spec.task_id.hex(), None)
            return False
        self.attempts[spec.task_id.hex()] = attempts + 1
        logger.warning(
            "retrying actor task %s (attempt %d/%d) after: %s",
            spec.name, attempts + 1, spec.max_retries, err,
        )
        self.specs.put(spec)
        return True

    def _send_loop(self) -> None:
        w = self.worker
        while not w._shutdown.is_set():
            try:
                spec = self.specs.get(timeout=0.5)
            except queue.Empty:
                continue
            deps = w._pending_task_deps.pop(spec.task_id.hex(), None)
            if deps:
                # resolve arg dependencies before the send (reference
                # actor_task_submitter dependency wait); event-driven via
                # worker.wait, owner loss counts as resolved (the executor
                # surfaces the error at arg fetch)
                try:
                    w.wait(deps, num_returns=len(deps), timeout_s=None)
                except Exception:  # noqa: BLE001 — never wedge the sender
                    logger.exception(
                        "actor task %s dependency wait failed", spec.name
                    )
            # A failed *send* (frame never accepted by the socket) is safe
            # to retry against the restarted actor; once the frame is out,
            # failures are classified by _actor_connection_lost instead.
            addr = None
            for _ in range(3):
                try:
                    # Long bound: calls to an actor still pending creation /
                    # restart legitimately wait (reference blocks on the GCS
                    # actor table); probes that need a short bound pass
                    # their own timeout_s.
                    addr = w._resolve_actor_address(spec.actor_id, timeout_s=3600.0)
                    client = w.workers.get(addr)
                    pending = client.call_async("actor_task", spec=spec)
                    pending.add_done_callback(
                        lambda p, s=spec: self.completed.put((p, s))
                    )
                    break
                except (RpcConnectionError, RpcTimeout):
                    w._actor_addr_cache.pop(spec.actor_id, None)
                    if addr is not None:
                        w.workers.drop(addr)
                    time.sleep(0.1)
                    continue
                except Exception as e:  # noqa: BLE001
                    w._store_actor_task_failure(spec, e)
                    break
            else:
                err = w._actor_connection_lost(spec)
                if not self._maybe_retry(spec, err):
                    w._store_actor_task_failure(spec, err)

    def _wait_loop(self) -> None:
        w = self.worker
        while not w._shutdown.is_set():
            try:
                pending, spec = self.completed.get(timeout=0.5)
            except queue.Empty:
                continue
            try:
                reply = pending.wait(0)  # already done: no blocking
                self.attempts.pop(spec.task_id.hex(), None)
                w._store_task_reply(spec, reply)
            except (RpcConnectionError, RpcTimeout):
                err = w._actor_connection_lost(spec)
                if not self._maybe_retry(spec, err):
                    w._store_actor_task_failure(spec, err)
            except Exception as e:  # noqa: BLE001
                w._store_actor_task_failure(spec, e)


class _Lease:
    """A granted worker lease held by the owner's lease cache."""

    __slots__ = ("agent_addr", "worker_addr", "lease_id", "idle_since",
                 "client", "fresh")

    def __init__(self, agent_addr: str, worker_addr: str, lease_id: str):
        self.agent_addr = agent_addr
        self.worker_addr = worker_addr
        self.lease_id = lease_id
        self.idle_since = time.monotonic()
        self.client = None  # RpcClient, bound at first dispatch
        # True until the first dispatch: that one task paid the lease
        # RPC, every later one is a cache hit (rt_lease_cache_hits_total)
        self.fresh = True


class _NormalTaskSubmitter:
    """Per-scheduling-key lease cache + pipelined normal-task submission.

    Parity: the reference caches granted worker leases per SchedulingKey
    and pipelines queued tasks onto held workers instead of paying a
    lease round trip per task (reference
    src/ray/core_worker/task_submission/normal_task_submitter.h:52-82,
    worker_to_lease_entry_), with owner-side bounded lease requests (its
    max_pending_lease_requests). Steady state pays ZERO lease RPCs per
    task; an idle lease is returned to its agent after lease_keepalive_s.

    Threading: a mutex guards the queue/pool state; dispatch happens
    INLINE on whichever thread makes a lease available — the submitting
    thread when a cached lease is idle, the RPC read thread the moment a
    worker's reply lands (so a held worker gets its next task without a
    queue hop), the acquisition thread when a fresh lease is granted. A
    maintenance thread only sizes the pool while replies are stalled
    behind long tasks, reaps idle leases, and releases them at shutdown.

    Pool sizing is Little's law: hold enough workers to drain the queue
    in ~lease_rampup_target_s at the measured (EMA) per-task service
    latency. Short tasks pipeline onto a few warm workers — a worker
    process per nop task is pure context-switch overhead — while long
    tasks scale wide via stall detection (the oldest in-flight age
    overrides a stale-low EMA, so the pool grows before any slow reply
    lands).
    """

    def __init__(self, worker: CoreWorker, resources: Dict[str, float],
                 strategy, runtime_env=None):
        self.w = worker
        self.resources = dict(resources)
        self.strategy = strategy
        self.runtime_env = runtime_env
        self.lock = threading.Lock()
        self.pending: deque = deque()
        self.idle: List[_Lease] = []
        self.nbusy = 0
        self.requesting = 0
        self.attempts: Dict[str, int] = {}  # task hex -> attempts used
        # EMA of per-task service latency (dispatch -> reply); 10ms prior.
        # The key-wide EMA drives pool sizing; the per-FUNCTION EMA gates
        # batching — different fns share a scheduling key, and one slow fn
        # must never be coalesced on the strength of a fast fn's history.
        self._svc_latency = 0.01
        self._fn_lat: Dict[str, float] = {}
        self._dispatch_ts: Dict[str, float] = {}
        self._next_request_at = 0.0
        # dispatched calls whose done-callback is not yet registered:
        # arming happens OUTSIDE the lock (add_done_callback runs the
        # callback synchronously when the reply already landed, and
        # _on_done takes the lock — arming under it would self-deadlock)
        self._to_arm: List[tuple] = []
        self._arming = threading.local()
        self._sender_kicked = False
        self._empty_since: Optional[float] = None
        self._disposed = False

    def submit(self, spec: TaskSpec) -> bool:
        """False if this submitter was already disposed by the janitor
        (caller re-registers a fresh one)."""
        with self.lock:
            if self._disposed:
                return False
            self.pending.append(spec)
            self._flow_locked()
        # sends go to the pool, NOT inline: a caller submitting a burst
        # must not pay serialize+sendall per task — while the pool sender
        # works, later submits queue up and coalesce into fatter chunks
        # (replies, by contrast, send their next chunk inline to keep the
        # worker pipeline tight)
        self._kick_sender()
        return True

    def _kick_sender(self) -> None:
        with self.lock:
            if not self._to_arm or self._sender_kicked:
                return
            self._sender_kicked = True
        self.w._submit_pool.submit(self._drain_sends)

    def _drain_sends(self) -> None:
        try:
            self._arm_callbacks()
        finally:
            with self.lock:
                self._sender_kicked = False
            # items planned after _arm_callbacks drained but before the
            # flag cleared would strand: re-kick if any
        self._kick_sender()

    def _arm_callbacks(self) -> None:
        """Perform the actual sends for chunks the state machine planned
        under the lock. Runs with the lock RELEASED — the serialize +
        sendall of a push (~100us) must not sit in the critical section,
        where it would serialize every submitting thread against every
        reply thread. Reentrancy-guarded: a synchronously-completed reply
        runs _on_done inline, which can plan more sends and land back
        here."""
        if getattr(self._arming, "active", False):
            return  # the outer frame's drain loop will pick new items up
        self._arming.active = True
        try:
            while True:
                with self.lock:
                    if not self._to_arm:
                        return
                    items, self._to_arm = self._to_arm, []
                for lease in items:
                    self._send_chunk(lease)
        finally:
            self._arming.active = False

    def _send_chunk(self, lease: _Lease) -> None:
        """Bind up to a chunk of queued specs to this reserved lease and
        push them in one RPC. Runs OUTSIDE the lock (serialize+sendall
        must not serialize submitters against reply threads)."""
        w = self.w
        with self.lock:
            specs = self._take_chunk_locked()
            if not specs:
                # queue drained before this reservation got serviced
                self.nbusy -= 1
                lease.idle_since = time.monotonic()
                self.idle.append(lease)
                return
            now = time.monotonic()
            for spec in specs:
                w._inflight_push[spec.task_id.hex()] = lease.worker_addr
                self._dispatch_ts[spec.task_id.hex()] = now
        if core_metrics.ENABLED:
            # tasks that rode an ALREADY-PAID-FOR lease: the first
            # dispatch on a fresh grant is the one task its lease RPC
            # bought, every other is a cache hit
            hits = len(specs) - (1 if lease.fresh else 0)
            if hits:
                core_metrics.lease_cache_hits.inc(hits)
        lease.fresh = False
        if tracing.ENABLED:
            for spec in specs:
                w._append_task_event(tracing.lifecycle_event(
                    tracing.DISPATCHED, spec.task_id.hex(), spec.name,
                    w.address, target=lease.worker_addr,
                ))
        try:
            client = lease.client
            if client is None:
                client = lease.client = w.workers.get(lease.worker_addr)
            pending = client.call_async("push_tasks", specs=specs)
        except (RpcError, OSError):
            w.workers.drop(lease.worker_addr)
            # release off-thread: a dead agent must not stall this
            # (submit or reply) thread for a connect timeout
            w._submit_pool.submit(self._release, lease, True)
            with self.lock:
                self.nbusy -= 1
                for spec in specs:
                    w._inflight_push.pop(spec.task_id.hex(), None)
                    self._dispatch_ts.pop(spec.task_id.hex(), None)
                    self._retry_or_fail_locked(
                        spec,
                        WorkerCrashedError(
                            f"worker {lease.worker_addr} unreachable for "
                            f"{spec.name}"
                        ),
                    )
                self._flow_locked()
            return
        pending.add_done_callback(
            lambda p, s=specs, l=lease: self._on_done(p, s, l)
        )

    # -- state machine (lock held) --------------------------------------

    def _flow_locked(self) -> None:
        """Reserve idle leases for queued specs, then size the pool. A
        reservation carries the LEASE only — the specs are taken at SEND
        time (_send_chunk), so during a submit flood the (slower, pooled)
        sender finds a fattened queue and coalesces many specs per RPC
        instead of freezing chunk boundaries at plan time."""
        while self.pending and self.idle:
            lease = self.idle.pop()  # LIFO: warmest worker first
            self.nbusy += 1
            self._to_arm.append(lease)
        self._scale_locked()

    def _take_chunk_locked(self) -> List[TaskSpec]:
        """How many queued specs ride one push RPC. Tasks of a MEASURED
        sub-ms function coalesce (the ~100us frame roundtrip dominates
        them); anything slower — or not yet measured — goes one-per-RPC
        so a slow task never executes serially behind batch peers. A
        batch stops at a fn whose profile differs. Cancelled specs are
        consumed here (error stored) without entering the chunk."""
        w = self.w
        chunk: List[TaskSpec] = []
        cap = min(16, max(1, len(self.pending) // (len(self.idle) + 1)))
        while self.pending and len(chunk) < cap:
            spec = self.pending[0]
            task_hex = spec.task_id.hex()
            if task_hex in w._cancelled_tasks:
                self.pending.popleft()
                self.attempts.pop(task_hex, None)
                w._store_error_returns(
                    spec,
                    TaskCancelledError(f"task {spec.name} was cancelled"),
                )
                continue
            lat = self._fn_lat.get(spec.fn_id, 0.01)
            if lat >= 0.005:
                if not chunk:
                    chunk.append(self.pending.popleft())
                break  # slow fn: alone in its RPC, never behind peers
            chunk.append(self.pending.popleft())
        return chunk

    def _scale_locked(self) -> None:
        if not self.pending:
            return
        now = time.monotonic()
        held = self.nbusy + len(self.idle)
        lat = self._svc_latency
        # Stall detection: if the oldest in-flight task has been out much
        # longer than the EMA says tasks take, the pool is provably stuck
        # behind long tasks — scale on the observed age, uncapped (the
        # EMA alone would react only after those slow replies land).
        stalled = False
        if self._dispatch_ts:
            age = now - min(self._dispatch_ts.values())
            if age > max(3.0 * lat, 0.05):
                stalled = True
        if stalled:
            # demand is provably stuck behind long tasks: one lease per
            # stuck-or-queued task (busy leases count — each is pinned
            # under a long task, so queued work needs NEW workers, and the
            # resulting parked lease requests are exactly the demand
            # signal the autoscaler scales on), capped at 4x the pool per
            # 50ms tick so a transient reply gap can't fork a worker per
            # queue entry
            want = min(
                len(self.pending) + self.nbusy, max(held * 4, 8)
            )
        else:
            want = int(
                len(self.pending) * lat / float(config.lease_rampup_target_s)
            )
            if held > 0:
                # exponential ramp: at most double the pool per step, with
                # spacing between steps — a burst of short tasks must not
                # fork a worker per queue entry before the first replies
                # reveal the true service latency
                want = min(want, held * 2)
            want = min(want, len(self.pending))
        want = max(want, 1 if held == 0 else 0)
        need = want - self.requesting - held
        if need > 0 and (stalled or now >= self._next_request_at):
            cap = int(config.max_lease_requests_per_key)
            fired = False
            while need > 0 and self.requesting < cap:
                self.requesting += 1
                need -= 1
                fired = True
                self.w._submit_pool.submit(self._acquire_lease)
            if fired:
                self._next_request_at = now + 0.05

    def _retry_or_fail_locked(self, spec: TaskSpec, err: Exception) -> None:
        """Mirror of the pre-cache retry ladder (_submit_normal_task):
        connection/crash failures always retry; app-level TaskErrors only
        with retry_exceptions; anything else is terminal."""
        w = self.w
        task_hex = spec.task_id.hex()
        used = self.attempts.get(task_hex, 0) + 1
        self.attempts[task_hex] = used
        total = spec.max_retries + 1
        retryable = isinstance(
            err, (RpcConnectionError, RpcTimeout, WorkerCrashedError)
        ) or (isinstance(err, TaskError) and spec.retry_exceptions)
        if (
            retryable
            and used < total
            and task_hex not in w._cancelled_tasks
            and not w._shutdown.is_set()
        ):
            logger.warning(
                "task %s attempt %d/%d failed: %s",
                spec.name, used, total, err,
            )
            self.pending.append(spec)
            return
        self.attempts.pop(task_hex, None)
        if task_hex in w._cancelled_tasks:
            # a force-cancel kills the worker: the resulting connection
            # loss is the CANCELLATION landing, not a crash
            err = TaskCancelledError(f"task {spec.name} was cancelled")
        elif not isinstance(err, TaskError):
            err = TaskError(
                f"task {spec.name} failed after {used} attempts: {err}"
            )
        w._store_error_returns(spec, err)

    # -- reply path (RPC read thread) -----------------------------------

    def _on_done(self, pending, specs: List[TaskSpec], lease: _Lease) -> None:
        w = self.w
        now = time.monotonic()
        for spec in specs:
            w._inflight_push.pop(spec.task_id.hex(), None)
        with self.lock:
            self.nbusy -= 1
            ts = None
            for spec in specs:
                ts = self._dispatch_ts.pop(spec.task_id.hex(), None) or ts
            if ts is not None:
                # per-task share of the batch wall time; slow EMA so
                # transient contention (e.g. worker spawns stealing CPU)
                # doesn't read as "tasks got long" and trigger a
                # self-reinforcing scale-out spiral
                sample = (now - ts) / len(specs)
                self._svc_latency = (
                    0.95 * self._svc_latency + 0.05 * sample
                )
                for spec in specs:
                    prev = self._fn_lat.get(spec.fn_id, sample)
                    self._fn_lat[spec.fn_id] = 0.7 * prev + 0.3 * sample
        try:
            replies = pending.wait(0)  # already done: no blocking
        except (RpcConnectionError, RpcTimeout):
            for spec in specs:
                if spec.tensor_transport == "device":
                    # the executor may have parked device-resident returns
                    # before the reply was lost; free that HBM best-effort
                    # on the existing connection only
                    try:
                        c = w.workers.get(lease.worker_addr)
                        if c._sock is not None:
                            for i in range(max(spec.num_returns, 0)):
                                c.call_oneway(
                                    "free_device_object",
                                    obj_hex=ObjectID.from_task(
                                        spec.task_id, i
                                    ).hex(),
                                )
                    except RpcError:
                        pass
            w.workers.drop(lease.worker_addr)
            self._release(lease, kill=True)
            with self.lock:
                for spec in specs:
                    self._retry_or_fail_locked(
                        spec,
                        WorkerCrashedError(
                            f"worker {lease.worker_addr} died while "
                            f"executing {spec.name}"
                        ),
                    )
                self._flow_locked()
            self._arm_callbacks()
            return
        except Exception as e:  # noqa: BLE001 — RPC-level failure
            self._release(lease, kill=True)
            with self.lock:
                for spec in specs:
                    self._retry_or_fail_locked(spec, e)
                self._flow_locked()
            self._arm_callbacks()
            return
        # healthy worker: pipeline the next queued chunk onto it NOW —
        # inline on this reply thread, which keeps the worker's pipeline
        # tight (the submit path, by contrast, offloads sends to the pool)
        with self.lock:
            reserved = bool(self.pending)
            if reserved:
                self.nbusy += 1
            else:
                lease.idle_since = time.monotonic()
                self.idle.append(lease)
        if reserved:
            self._send_chunk(lease)
        retry = []
        for spec, reply in zip(specs, replies):
            task_hex = spec.task_id.hex()
            if (
                isinstance(reply, dict)
                and reply.get("status") == "interrupted"
            ):
                # a stray cancel interrupt hit this (innocent) task on
                # the executor: always retryable
                retry.append((
                    spec,
                    WorkerCrashedError(
                        f"task {spec.name} caught a stray cancel interrupt"
                    ),
                ))
                continue
            try:
                w._store_task_reply(spec, reply)
                with self.lock:
                    self.attempts.pop(task_hex, None)
            except TaskError as e:
                # retry_exceptions path: _store_task_reply re-raises the
                # app-level error so the task can retry
                retry.append((spec, e))
            except Exception as e:  # noqa: BLE001
                with self.lock:
                    self.attempts.pop(task_hex, None)
                w._store_error_returns(spec, e)
        if retry:
            with self.lock:
                for spec, e in retry:
                    self._retry_or_fail_locked(spec, e)
                self._flow_locked()
            self._arm_callbacks()

    # -- leases ---------------------------------------------------------

    def maintain_tick(self) -> bool:
        """One janitor sweep: stall scaling + idle-lease reaping (no
        submit/reply thread will run the pump while every reply is stuck
        behind a long task). Returns True when this submitter has been
        completely empty past the keepalive window and can be dropped —
        every distinct scheduling key (each PG strategy mints one) must
        not cost a live object forever."""
        cutoff = time.monotonic() - float(config.lease_keepalive_s)
        expired = []
        with self.lock:
            # _flow (not just _scale): a rare failed dispatch re-queues
            # its spec without an event to pick it up — sweep it onto
            # an idle lease here
            self._flow_locked()
            if self.idle and self.idle[0].idle_since < cutoff:
                keep = []
                for lease in self.idle:
                    (keep if lease.idle_since >= cutoff
                     else expired).append(lease)
                self.idle = keep
            empty = not (
                self.pending or self.idle or self.nbusy or self.requesting
            )
            if not empty:
                self._empty_since = None
            elif self._empty_since is None:
                self._empty_since = time.monotonic()
            disposable = (
                empty
                and self._empty_since is not None
                and time.monotonic() - self._empty_since > 60.0
            )
        self._arm_callbacks()
        for lease in expired:
            self._release(lease, kill=False)
        return disposable

    def try_dispose(self) -> bool:
        """Mark disposed iff still completely empty (janitor sweep)."""
        with self.lock:
            if (
                self.pending or self.idle or self.nbusy or self.requesting
            ):
                return False
            self._disposed = True
            return True

    def release_all(self) -> None:
        """Shutdown: hand every idle lease back (best effort)."""
        with self.lock:
            leases, self.idle = self.idle, []
        for lease in leases:
            self._release(lease, kill=False)

    def _release(self, lease: _Lease, kill: bool) -> None:
        try:
            self.w.agents.get(lease.agent_addr).call_oneway(
                "release_worker", lease_id=lease.lease_id, kill=kill
            )
        except RpcError:
            pass

    def _on_lease(self, lease: _Lease) -> None:
        with self.lock:
            self.requesting -= 1
            self.idle.append(lease)
            self._flow_locked()
        self._arm_callbacks()

    def _on_no_lease(self, err: Optional[Exception], fatal: bool) -> None:
        specs = []
        with self.lock:
            self.requesting -= 1
            if fatal:
                while self.pending:
                    spec = self.pending.popleft()
                    self.attempts.pop(spec.task_id.hex(), None)
                    specs.append(spec)
            elif err is not None:
                # transient acquisition failure: back off briefly so a
                # dead agent isn't hammered in a tight loop
                self._next_request_at = time.monotonic() + 0.2
        # the key is unschedulable (hard scheduler error): every queued
        # spec gets the same verdict — identical resources/strategy mean
        # an identical outcome, per-spec retries would all see it again
        for spec in specs:
            self.w._store_error_returns(
                spec,
                TaskError(
                    f"task {spec.name} unschedulable: {err} "
                    f"(resources={self.resources})"
                ),
            )

    def _acquire_lease(self) -> None:
        """Blocking lease acquisition with spillback hops; runs on the
        submit pool. Reports exactly one _on_lease/_on_no_lease."""
        w = self.w
        strategy = self.strategy
        bundle = None
        if isinstance(strategy, dict) and strategy.get("type") == "placement_group":
            bundle = (strategy["pg_id"], strategy.get("bundle_index"))
        agent = w.agent
        agent_addr = w.node_agent_address
        hops = 0
        try:
            while True:
                if w._shutdown.is_set() or not self.pending:
                    # demand evaporated while we waited (tasks were served
                    # by cached leases, or cancelled)
                    self._on_no_lease(None, False)
                    return
                try:
                    lease = agent.call(
                        "lease_worker",
                        resources=self.resources,
                        bundle=bundle,
                        strategy=strategy,
                        wait_s=5.0,
                        timeout_s=20.0,
                        runtime_env=self.runtime_env,
                    )
                except (RpcConnectionError, RpcTimeout) as e:
                    if isinstance(e, RpcConnectionError):
                        # possibly our own agent died (driver outlives its
                        # node): re-attach before the next attempt
                        w._maybe_reattach_agent()
                    self._on_no_lease(e, False)
                    return
                if lease.get("granted"):
                    granted = _Lease(
                        agent_addr, lease["worker_address"],
                        lease["lease_id"],
                    )
                    # bind + connect the worker client HERE (pool thread,
                    # no lock): the first dispatch otherwise pays the TCP
                    # connect under the submitter lock
                    try:
                        granted.client = w.workers.get(granted.worker_addr)
                        granted.client.connect()
                    except RpcError:
                        pass  # dispatch's failure path handles it
                    if tracing.ENABLED:
                        w._append_task_event(tracing.lifecycle_event(
                            tracing.LEASE_GRANTED, granted.lease_id,
                            "lease", w.address,
                            target=granted.worker_addr,
                        ))
                    self._on_lease(granted)
                    return
                spill = lease.get("spillback")
                if spill:
                    hops += 1
                    if hops > 16:
                        self._on_no_lease(
                            TaskError("too many spillback hops"), True
                        )
                        return
                    agent = w.agents.get(spill)
                    agent_addr = spill
                    continue
                if lease.get("error") == "lease timeout":
                    # stay queued (reference: leases wait); the agent
                    # answers instantly for pending PGs, so back off
                    # briefly to avoid a tight loop
                    time.sleep(0.2)
                    continue
                self._on_no_lease(TaskError(str(lease.get("error"))), True)
                return
        except Exception as e:  # noqa: BLE001 — never leak `requesting`
            self._on_no_lease(e, False)
