"""Control store — the cluster control plane (GCS equivalent).

Parity: the reference GCS server (src/ray/gcs/gcs_server.h:96) and its
managers: node membership + health checks (GcsNodeManager,
gcs_health_check_manager.h:45), actor directory + FT scheduling
(GcsActorManager src/ray/gcs/actor/gcs_actor_manager.h:93, restart logic
gcs_actor_manager.cc:1477-1506), placement groups with 2-phase commit
(GcsPlacementGroupManager gcs_placement_group_manager.h:50, PREPARE/COMMIT
gcs_placement_group_scheduler.h:115-117), jobs (GcsJobManager), KV store
(store_client.h — in-memory here, pluggable), pubsub (src/ray/pubsub/), and
the resource-view syncer (src/ray/ray_syncer/ray_syncer.h:91 — here:
heartbeat-carried resource reports fanned out on a pubsub topic).

Runs as threads inside the head process; all state in-memory (a persistence
hook mirrors the Redis-backed FT mode and can be added behind StoreBackend).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import scheduling
from ray_tpu.observability import core_metrics
from ray_tpu.utils.config import config
from ray_tpu.utils.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.utils.rpc import ClientPool, RpcError, RpcServer

logger = logging.getLogger(__name__)


class ActorState:
    DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
    PENDING_CREATION = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class PGState:
    PENDING = "PENDING"
    CREATED = "CREATED"
    REMOVED = "REMOVED"
    RESCHEDULING = "RESCHEDULING"


class ControlStore:
    def __init__(self, session_id: str, host: str = "127.0.0.1", port: int = 0,
                 persistence_path: Optional[str] = None):
        self.session_id = session_id
        # Pluggable metadata persistence (reference C14: in-memory default
        # vs Redis FT mode): with a path, the KV and job tables snapshot
        # to disk and a restarted control store restores them (cluster
        # membership and worker state re-register via heartbeats).
        self._persistence_path = persistence_path or (
            str(config.control_store_persistence_path) or None
        )
        self._dirty = False
        self._server = RpcServer("control_store", host, port)
        self._server.register_instance(self)
        self._server.on_disconnect = self._handle_disconnect

        self._lock = threading.RLock()
        self._kv: Dict[str, Dict[str, bytes]] = {}
        self._kv_cv = threading.Condition(self._lock)
        self._nodes: Dict[str, Dict[str, Any]] = {}  # node_id hex -> record
        self._actors: Dict[str, Dict[str, Any]] = {}  # actor_id hex -> record
        self._named_actors: Dict[Tuple[str, str], str] = {}
        self._pgs: Dict[str, Dict[str, Any]] = {}
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._next_job = 1

        # pubsub: topic -> {conn_id: conn}
        self._subs: Dict[str, Dict[int, Any]] = {}

        # aggregate resource-view version: bumps on any node join/leave or
        # resource change (versioned sync, reference ray_syncer.h:91)
        self._view_version = 0

        self._agents = ClientPool("cs->agent")
        self._workers = ClientPool("cs->worker")
        self._stopped = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

        # Scheduling queue (reference GcsActorScheduler/PG scheduler run
        # on the GCS io-service, not a thread per entity): ONE dispatcher
        # thread drains this queue; lease/create RPCs go out async and
        # their completions re-enqueue follow-up items, so thread count
        # stays flat no matter how many actors/PGs are pending.
        self._sched_q: "queue.Queue" = queue.Queue()
        self._sched_retries: List[Tuple[float, int, tuple]] = []  # heap
        self._sched_seq = itertools.count()
        self._sched_backoff: Dict[tuple, float] = {}
        self._sched_retry_lock = threading.Lock()  # heap+backoff (pg pool
        # threads and the dispatcher both retry/enqueue)
        # PG 2PC does synchronous prepare/commit RPCs; a hung agent must
        # not stall the (async) actor pipeline, so PG passes run on a
        # small fixed pool instead of the dispatcher thread.
        from concurrent.futures import ThreadPoolExecutor

        self._pg_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="cs-pg"
        )
        self._pg_running: set = set()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._restore()
        self._server.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="cs-health", daemon=True
        )
        self._health_thread.start()
        threading.Thread(
            target=self._sched_loop, name="cs-scheduler", daemon=True
        ).start()
        if self._persistence_path:
            threading.Thread(
                target=self._persist_loop, name="cs-persist", daemon=True
            ).start()

    def stop(self) -> None:
        self._stopped.set()
        self._pg_pool.shutdown(wait=False)
        self._persist(force=True)
        self._server.stop()
        self._agents.close_all()
        self._workers.close_all()

    # -- persistence (reference C14: gcs_table_storage + store_client) --

    def _restore(self) -> None:
        if not self._persistence_path or not os.path.exists(
            self._persistence_path
        ):
            return
        import pickle

        try:
            with open(self._persistence_path, "rb") as f:
                snap = pickle.load(f)
            with self._lock:
                self._kv = snap.get("kv", {})
                self._jobs = snap.get("jobs", {})
                self._next_job = snap.get("next_job", 1)
            logger.info(
                "control store restored %d KV namespaces, %d jobs from %s",
                len(self._kv), len(self._jobs), self._persistence_path,
            )
        except Exception:  # noqa: BLE001 — corrupt snapshot: start fresh
            logger.exception("control store snapshot restore failed")

    def _persist(self, force: bool = False) -> None:
        if not self._persistence_path or not (self._dirty or force):
            return
        import pickle

        with self._lock:
            snap = {
                # Collective rendezvous namespaces (coll/*) are
                # incarnation-scoped: restoring them would satisfy a new
                # group's barrier/op tags with a dead run's keys and
                # return stale tensors as wrong results.
                "kv": {
                    ns: dict(t) for ns, t in self._kv.items()
                    if not ns.startswith("coll/")
                },
                "jobs": {j: dict(r) for j, r in self._jobs.items()},
                "next_job": self._next_job,
            }
            self._dirty = False
        tmp = self._persistence_path + ".tmp"
        try:
            os.makedirs(
                os.path.dirname(os.path.abspath(self._persistence_path)),
                exist_ok=True,
            )
            with open(tmp, "wb") as f:
                pickle.dump(snap, f)
            os.replace(tmp, self._persistence_path)
        except OSError:
            logger.exception("control store snapshot write failed")

    def _persist_loop(self) -> None:
        while not self._stopped.wait(1.0):
            self._persist()

    @property
    def address(self) -> str:
        return self._server.address

    # ------------------------------------------------------------------
    # pubsub (reference C16)
    # ------------------------------------------------------------------

    def rpc_subscribe(self, conn, topics: List[str]):
        with self._lock:
            for t in topics:
                self._subs.setdefault(t, {})[id(conn)] = conn
        return True

    def rpc_publish(self, conn, topic: str, payload: Any):
        self.publish(topic, payload)
        return True

    def publish(self, topic: str, payload: Any) -> None:
        with self._lock:
            conns = list(self._subs.get(topic, {}).values())
        for c in conns:
            if not c.push("pubsub", (topic, payload)):
                with self._lock:
                    self._subs.get(topic, {}).pop(id(c), None)

    def _handle_disconnect(self, conn) -> None:
        with self._lock:
            for subs in self._subs.values():
                subs.pop(id(conn), None)
        node_id = getattr(conn, "node_id", None)
        if node_id is not None:
            # Fast failure detection: the agent's heartbeat connection
            # broke. Confirm with a short grace (a live agent re-heartbeats
            # on a fresh connection within one period) before declaring
            # death — much faster than the full health_check_timeout_s.
            threading.Thread(
                target=self._confirm_node_death, args=(node_id,),
                name="cs-conn-death", daemon=True,
            ).start()

    def _confirm_node_death(self, node_id: str) -> None:
        t_break = time.monotonic()
        grace = 2.5 * config.health_check_period_s
        while time.monotonic() - t_break < grace:
            if self._stopped.wait(0.25):
                return
            with self._lock:
                node = self._nodes.get(node_id)
                if node is None or not node["alive"]:
                    return
                if node["last_heartbeat"] > t_break:
                    return  # re-heartbeated on a fresh connection: alive
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"] or (
                node["last_heartbeat"] > t_break
            ):
                return
        logger.warning(
            "node %s heartbeat connection lost; marking dead", node_id[:8]
        )
        self._mark_node_dead(node_id, "heartbeat connection lost")

    # ------------------------------------------------------------------
    # KV (reference C14 / internal KV)
    # ------------------------------------------------------------------

    def rpc_kv_put(self, conn, ns: str, key: str, value: bytes, overwrite: bool = True):
        with self._lock:
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            self._dirty = True
            self._kv_cv.notify_all()
            return True

    def rpc_kv_get(self, conn, ns: str, key: str):
        with self._lock:
            return self._kv.get(ns, {}).get(key)

    def rpc_kv_wait(self, conn, ns: str, key: str, wait_s: float = 60.0):
        """Block server-side until the key exists (or timeout); returns
        the value or None. The collective tier's rendezvous primitive:
        one blocking RPC replaces a client-side poll loop (the round-2
        O(n^2)-polling weakness)."""
        deadline = time.monotonic() + wait_s
        with self._lock:
            while True:
                val = self._kv.get(ns, {}).get(key)
                if val is not None:
                    return val
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped.is_set():
                    return None
                self._kv_cv.wait(min(remaining, 1.0))

    def rpc_kv_del(self, conn, ns: str, key: str):
        with self._lock:
            self._dirty = True
            return self._kv.get(ns, {}).pop(key, None) is not None

    def rpc_kv_keys(self, conn, ns: str, prefix: str = ""):
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    def rpc_kv_del_prefix(self, conn, ns: str, prefix: str = ""):
        with self._lock:
            self._dirty = True
            table = self._kv.get(ns)
            if table is None:
                return 0
            doomed = [k for k in table if k.startswith(prefix)]
            for k in doomed:
                del table[k]
            if not table and prefix == "":
                self._kv.pop(ns, None)
            return len(doomed)

    # ------------------------------------------------------------------
    # nodes (reference GcsNodeManager + health checks + syncer)
    # ------------------------------------------------------------------

    def rpc_register_node(self, conn, node_info: Dict[str, Any]):
        node_id = node_info["node_id"]
        with self._lock:
            self._nodes[node_id] = {
                **node_info,
                "alive": True,
                "last_heartbeat": time.monotonic(),
                "resources_available": dict(node_info["resources_total"]),
            }
            self._view_version += 1
        logger.info("node %s registered at %s", node_id[:8], node_info["address"])
        self.publish("node", {"event": "added", "node": self._public_node(node_id)})
        # fresh capacity: retry anything the scheduler had parked
        self._sched_enqueue(("kick",))
        return {"config_snapshot": config.snapshot(), "session_id": self.session_id}

    def rpc_heartbeat(self, conn, node_id: str,
                      resources_available: Optional[Dict[str, float]] = None,
                      extra: Optional[Dict[str, Any]] = None,
                      pending_leases: int = 0, active_leases: int = 0,
                      view_version: Optional[int] = None):
        """Versioned resource-view sync (reference ray_syncer.h:91):
        resources_available=None is a LIGHT beat — liveness only, the
        resource view is unchanged at `view_version`. A version mismatch
        (store restarted / payload lost) asks the agent to resync with a
        full beat."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"]:
                return {"ok": False}  # tells a zombie agent to exit
            # Tag the transport so a broken agent connection fast-paths
            # failure detection (reference: GCS treats the raylet channel
            # break as a death signal, not just missed heartbeats).
            conn.node_id = node_id
            node["last_heartbeat"] = time.monotonic()
            if resources_available is None:
                if node.get("view_version") != view_version:
                    return {"ok": True, "resync": True}
                return {"ok": True}
            node["resources_available"] = resources_available
            node["pending_leases"] = pending_leases
            node["active_leases"] = active_leases
            node["view_version"] = view_version
            if extra:
                node.update(extra)
            self._view_version += 1
        return {"ok": True}

    def rpc_capacity_freed(self, conn, node_id: str):
        """A lease was released on `node_id`: retry parked scheduling work
        immediately instead of waiting out its backoff (ADVICE r4: pending
        actors otherwise idle up to 2s after capacity frees). Coalesced:
        on a busy cluster every release fires this, so kicks within 100ms
        collapse to one — a dropped kick only costs one short backoff step
        (heartbeat anti-entropy is the backstop)."""
        now = time.monotonic()
        if now - getattr(self, "_last_kick_req", 0.0) >= 0.1:
            self._last_kick_req = now
            self._sched_enqueue(("kick",))
        return {"ok": True}

    def rpc_get_nodes(self, conn, alive_only: bool = True):
        with self._lock:
            return [
                self._public_node(nid)
                for nid, n in self._nodes.items()
                if n["alive"] or not alive_only
            ]

    def rpc_get_cluster_view(self, conn, known_version: Optional[int] = None):
        """Scheduling view: per-node totals/availables (syncer
        equivalent). With known_version, reply {"unchanged": True} when
        the aggregate view hasn't moved — consumers polling the view
        (autoscaler, elastic train) pay O(1) instead of O(nodes)."""
        with self._lock:
            if known_version is not None:
                if known_version == self._view_version:
                    return {"unchanged": True, "version": self._view_version}
                return {
                    "version": self._view_version,
                    "view": self._cluster_view_locked(),
                }
            return self._cluster_view_locked()

    def rpc_drain_node(self, conn, node_id: str):
        self._mark_node_dead(node_id, "drained")
        return True

    def rpc_get_metrics(self, conn):
        """This process's metric registry (built-in scheduler series live
        here). The token lets state.cluster_metrics dedup the head case
        where control store + agent + driver share one process."""
        from ray_tpu.utils import metrics as metrics_mod

        return {
            "token": metrics_mod.PROCESS_TOKEN,
            "metrics": metrics_mod.snapshot_all(),
        }

    def _public_node(self, node_id: str) -> Dict[str, Any]:
        n = self._nodes[node_id]
        return {
            "node_id": node_id,
            "address": n["address"],
            "resources_total": n["resources_total"],
            "labels": n.get("labels", {}),
            "alive": n["alive"],
            "pending_leases": n.get("pending_leases", 0),
            "active_leases": n.get("active_leases", 0),
            "pending_shapes": n.get("pending_shapes", []),
        }

    def _health_loop(self) -> None:
        while not self._stopped.wait(config.health_check_period_s):
            now = time.monotonic()
            dead = []
            with self._lock:
                for nid, n in self._nodes.items():
                    if n["alive"] and now - n["last_heartbeat"] > config.health_check_timeout_s:
                        dead.append(nid)
            for nid in dead:
                logger.warning("node %s missed heartbeats; marking dead", nid[:8])
                self._mark_node_dead(nid, "heartbeat timeout")

    def _mark_node_dead(self, node_id: str, reason: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"]:
                return
            node["alive"] = False
            self._view_version += 1
            affected_actors = [
                a for a in self._actors.values()
                if a.get("node_id") == node_id
                and a["state"] in (ActorState.ALIVE, ActorState.PENDING_CREATION)
            ]
            # PGs with a bundle on the dead node drop ONLY the lost bundle
            # locations and go back to PENDING for partial re-placement
            # (reference: GcsPlacementGroupManager reschedules on node
            # death); survivors' bundles — and the actors in them — keep
            # running. Without this, leases against the PG fail forever
            # with "bundle not found".
            replaced_pgs = []
            for pg in self._pgs.values():
                if pg["state"] != PGState.CREATED:
                    continue
                lost = [
                    i for i, nid in pg["bundle_locations"].items()
                    if nid == node_id
                ]
                if lost:
                    for i in lost:
                        del pg["bundle_locations"][i]
                    pg["state"] = PGState.PENDING
                    replaced_pgs.append(pg["pg_id"])
        self.publish("node", {"event": "removed", "node_id": node_id, "reason": reason})
        for actor in affected_actors:
            self._on_actor_worker_lost(actor["actor_id"], f"node died: {reason}")
        for pg_id in replaced_pgs:
            self._sched_enqueue(("pg", pg_id))

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    def rpc_register_job(self, conn, driver_address: str, metadata: Dict[str, Any]):
        with self._lock:
            job_id = JobID.from_int(self._next_job)
            self._next_job += 1
            self._jobs[job_id.hex()] = {
                "job_id": job_id.hex(),
                "driver_address": driver_address,
                "metadata": metadata,
                "start_time": time.time(),
                "alive": True,
            }
            self._dirty = True
        return job_id.hex()

    def rpc_finish_job(self, conn, job_id: str):
        with self._lock:
            job = self._jobs.get(job_id)
            if job:
                job["alive"] = False
                job["end_time"] = time.time()
                self._dirty = True
        # Non-detached actors owned by the job die with it.
        with self._lock:
            doomed = [
                a["actor_id"] for a in self._actors.values()
                if a.get("job_id") == job_id
                and a.get("lifetime") != "detached"
                and a["state"] not in (ActorState.DEAD,)
            ]
        for aid in doomed:
            self._kill_actor_internal(aid, "job finished", no_restart=True)
        return True

    def rpc_list_jobs(self, conn):
        with self._lock:
            return list(self._jobs.values())

    # ------------------------------------------------------------------
    # actors (reference C2: GcsActorManager + GcsActorScheduler)
    # ------------------------------------------------------------------

    def rpc_register_actor(self, conn, spec: Dict[str, Any]):
        """Register + asynchronously schedule an actor.

        spec: actor_id, job_id, class_blob_key, init args (by value or refs),
        resources, name/namespace, lifetime, max_restarts, max_concurrency,
        scheduling_strategy, owner_address.
        """
        actor_id = spec["actor_id"]
        name = spec.get("name")
        ns = spec.get("namespace", "default")
        with self._lock:
            if name:
                key = (ns, name)
                if key in self._named_actors:
                    existing = self._named_actors[key]
                    if self._actors[existing]["state"] != ActorState.DEAD:
                        raise ValueError(
                            f"actor name {name!r} already taken in namespace {ns!r}"
                        )
                self._named_actors[key] = actor_id
            record = {
                **spec,
                "state": ActorState.PENDING_CREATION,
                "num_restarts": 0,
                "node_id": None,
                "worker_address": None,
                "death_cause": None,
            }
            self._actors[actor_id] = record
        self._sched_enqueue(("actor", actor_id))
        return True

    # -- scheduling queue (reference: GcsActorScheduler + PG scheduler on
    # -- the GCS io-service; one dispatcher, async RPC continuations) ----

    def _sched_enqueue(self, item: tuple) -> None:
        # queue entries carry their enqueue time so the dispatcher can
        # report queue-wait (rt_sched_dispatch_latency_s) — the "which
        # queue is the bottleneck" signal at pod scale
        self._sched_q.put((time.monotonic(), item))
        if core_metrics.ENABLED:
            core_metrics.sched_queue_depth.set(self._sched_q.qsize())

    def _sched_retry(self, item: tuple, key: tuple) -> None:
        """Re-enqueue after this key's (exponential, capped) backoff.
        The 10s cap is a background anti-entropy poll, not the wake-up
        path: capacity_freed kicks requeue parked items the moment a
        lease frees, so thousands of unplaceable actors idle at ~0.1
        pass/s each instead of hammering the dispatcher at the old 2s
        cap (0.5 pass/s x 2000 pending saturated it)."""
        with self._sched_retry_lock:
            backoff = self._sched_backoff.get(key, 0.05)
            self._sched_backoff[key] = min(backoff * 2, 10.0)
            heapq.heappush(
                self._sched_retries,
                (time.monotonic() + backoff, next(self._sched_seq), item),
            )

    def _sched_kick(self) -> None:
        """Cluster capacity changed (node joined / lease freed / worker
        spawned): retry everything now, and reset the kicked keys' backoff
        so a retry that races the freed capacity (e.g. replacement worker
        still booting) re-polls at 50ms instead of the 2s cap."""
        with self._sched_retry_lock:
            items = [it for _, _, it in self._sched_retries]
            self._sched_retries.clear()
            for it in items:
                # HALVE (not clear) the backoff: the kick itself is the
                # immediate retry, and a later capacity event kicks again
                # — but a permanently-unplaceable item on a high-churn
                # cluster must keep re-climbing toward the cap instead of
                # running a full placement pass per kick at the 50ms floor
                key = tuple(it[:2])
                if key in self._sched_backoff:
                    self._sched_backoff[key] = max(
                        0.05, self._sched_backoff[key] / 2
                    )
        for it in items:
            self._sched_enqueue(it)

    def _sched_loop(self) -> None:
        while not self._stopped.is_set():
            now = time.monotonic()
            ready = []
            with self._sched_retry_lock:
                while self._sched_retries and self._sched_retries[0][0] <= now:
                    _, _, item = heapq.heappop(self._sched_retries)
                    ready.append(item)
                timeout = 0.5
                if self._sched_retries:
                    timeout = min(timeout, self._sched_retries[0][0] - now)
            for item in ready:
                self._sched_enqueue(item)
            try:
                enq_ts, item = self._sched_q.get(timeout=max(timeout, 0.005))
            except queue.Empty:
                continue
            if core_metrics.ENABLED:
                core_metrics.sched_queue_depth.set(self._sched_q.qsize())
                core_metrics.sched_dispatch_latency_s.observe(
                    time.monotonic() - enq_ts, tags={"kind": str(item[0])}
                )
            try:
                self._process_sched(item)
            except Exception:  # noqa: BLE001 — scheduler must survive
                logger.exception("scheduler item %r failed", item)
                # never DROP a pending entity on a scheduling crash: retry
                # with the key's backoff (capped), so a transient error
                # (node died mid-pass) can't orphan an actor/PG forever
                if item and item[0] in ("actor", "pg"):
                    self._sched_retry(item, tuple(item[:2]))

    def _process_sched(self, item: tuple) -> None:
        kind = item[0]
        if kind == "actor":
            self._sched_actor_place(item[1])
        elif kind == "actor_lease":
            self._sched_actor_leased(*item[1:])
        elif kind == "actor_created":
            self._sched_actor_created(*item[1:])
        elif kind == "pg":
            pg_id = item[1]
            with self._lock:
                if pg_id in self._pg_running:
                    # a pass for this PG is already on the pool: coalesce
                    # (it re-enqueues itself on progress/backoff)
                    self._sched_retry(("pg", pg_id), ("pg", pg_id))
                    return
                self._pg_running.add(pg_id)

            def run(pg_id=pg_id):
                again = False
                try:
                    again = bool(self._schedule_pg_once(pg_id))
                finally:
                    with self._lock:
                        self._pg_running.discard(pg_id)
                    if again:
                        # enqueue only AFTER leaving _pg_running: enqueueing
                        # inside the pass would hit the coalesce branch and
                        # defer the (usually final) CREATED transition by a
                        # backoff cycle
                        self._sched_enqueue(("pg", pg_id))

            self._pg_pool.submit(run)
        elif kind == "kick":
            self._sched_kick()

    def _sched_actor_place(self, actor_id: str) -> None:
        """Step 1: pick a node and fire an async lease request."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] in (
                ActorState.DEAD, ActorState.ALIVE,
            ):
                return
            view = self._cluster_view_locked()
            strategy = record.get("scheduling_strategy")
            resources = record.get("resources", {})
        node_id = scheduling.pick_node(
            view, resources, strategy, self._pgs, self._lock
        )
        if node_id is None or node_id not in view:
            # not in view: a PG-bundle pick can name a node that died
            # after the snapshot — retry (the PG re-places its bundle)
            # rather than KeyError-ing the item out of the queue
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        agent_addr = view[node_id]["address"]
        try:
            pend = self._agents.get(agent_addr).call_async(
                "lease_worker",
                resources=resources,
                bundle=scheduling.pg_bundle_of(strategy),
                wait_s=0.0,
                # actor leases are store-managed: a transient store->agent
                # reconnect must not reap every actor on the node
                bind_to_conn=False,
                runtime_env=record.get("runtime_env"),
            )
        except RpcError as e:
            logger.warning(
                "actor %s lease on %s failed: %s", actor_id[:8], node_id[:8], e
            )
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        pend.add_done_callback(
            lambda p: self._sched_enqueue(
                ("actor_lease", actor_id, node_id, agent_addr, p)
            )
        )

    def _sched_actor_leased(self, actor_id, node_id, agent_addr, pend) -> None:
        """Step 2: lease reply arrived; fire async actor creation."""
        try:
            lease = pend.wait(0)
        except RpcError as e:
            logger.warning("actor %s lease failed: %s", actor_id[:8], e)
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        if not lease.get("granted"):
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        worker_addr = lease["worker_address"]
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                # killed while scheduling; return the lease
                try:
                    self._agents.get(agent_addr).call_oneway(
                        "release_worker", lease_id=lease["lease_id"], kill=False
                    )
                except RpcError:
                    pass
                return
            spec = dict(record)
        try:
            pend2 = self._workers.get(worker_addr).call_async(
                "create_actor", spec=spec
            )
        except RpcError as e:
            logger.warning(
                "actor %s creation on %s failed: %s", actor_id[:8], worker_addr, e
            )
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        pend2.add_done_callback(
            lambda p: self._sched_enqueue(
                ("actor_created", actor_id, node_id, agent_addr, lease, p)
            )
        )

    def _sched_actor_created(
        self, actor_id, node_id, agent_addr, lease, pend
    ) -> None:
        """Step 3: creation reply arrived; finalize ALIVE/DEAD/retry."""
        try:
            created = pend.wait(0)
        except RpcError as e:
            # transport failure: worker unusable, retry elsewhere
            logger.warning(
                "actor %s creation push failed: %s", actor_id[:8], e
            )
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        if not created.get("ok"):
            # __init__ raised: permanent, surface the error to callers
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            with self._lock:
                record = self._actors.get(actor_id)
                if record is not None:
                    record["state"] = ActorState.DEAD
                    record["death_cause"] = str(created.get("error"))
            self._sched_backoff.pop(("actor", actor_id), None)
            self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
            self.publish("actor", self._public_actor(actor_id))
            return
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None:
                return
            if record["state"] == ActorState.DEAD:
                # killed while the creation push was in flight: the reply
                # must NOT resurrect it — tear the fresh worker down
                # (kill_actor found no worker_address to clean up yet)
                dead = True
            else:
                dead = False
                record["state"] = ActorState.ALIVE
                record["node_id"] = node_id
                record["worker_address"] = lease["worker_address"]
                record["lease_id"] = lease["lease_id"]
                record["agent_address"] = agent_addr
        if dead:
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            return
        self._sched_backoff.pop(("actor", actor_id), None)
        self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
        self.publish("actor", self._public_actor(actor_id))

    def rpc_get_actor_info(self, conn, actor_id: str):
        with self._lock:
            if actor_id not in self._actors:
                return None
            return self._public_actor(actor_id)

    def rpc_wait_actor_alive(self, conn, actor_id: str, wait_s: float = 60.0):
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                record = self._actors.get(actor_id)
                if record is None:
                    return None
                if record["state"] in (ActorState.ALIVE, ActorState.DEAD):
                    return self._public_actor(actor_id)
            time.sleep(0.02)
        with self._lock:
            return self._public_actor(actor_id) if actor_id in self._actors else None

    def rpc_get_named_actor(self, conn, name: str, namespace: str = "default"):
        with self._lock:
            actor_id = self._named_actors.get((namespace, name))
            if actor_id is None:
                return None
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return None
            return self._public_actor(actor_id)

    def rpc_list_actors(self, conn):
        with self._lock:
            return [self._public_actor(aid) for aid in self._actors]

    def rpc_report_actor_death(self, conn, actor_id: str, reason: str,
                               expected: bool = False):
        """Called by agents/workers when an actor's worker process exits."""
        if expected:
            self._kill_actor_internal(actor_id, reason, no_restart=True)
        else:
            self._on_actor_worker_lost(actor_id, reason)
        return True

    def rpc_report_worker_failure(self, conn, worker_address: str, node_id: str,
                                  reason: str):
        """A worker process died; fail over any actor it hosted."""
        with self._lock:
            affected = [
                a["actor_id"] for a in self._actors.values()
                if a.get("worker_address") == worker_address
                and a["state"] in (ActorState.ALIVE, ActorState.PENDING_CREATION)
            ]
        self._workers.drop(worker_address)
        for actor_id in affected:
            self._on_actor_worker_lost(actor_id, reason)
        self.publish("worker", {"event": "died", "worker_address": worker_address,
                                "node_id": node_id, "reason": reason})
        return True

    def rpc_kill_actor(self, conn, actor_id: str, no_restart: bool = True):
        self._kill_actor_internal(actor_id, "ray_tpu.kill", no_restart=no_restart)
        return True

    def rpc_actor_handle_dropped(self, conn, actor_id: str):
        """The original handle went out of scope: GC the actor unless it is
        detached (parity: GcsActorManager handle-count GC)."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record.get("lifetime") == "detached":
                return False
        self._kill_actor_internal(
            actor_id, "all handles to the actor went out of scope",
            no_restart=True,
        )
        return True

    def _kill_actor_internal(self, actor_id: str, reason: str, no_restart: bool) -> None:
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return
            worker_addr = record.get("worker_address")
            agent_addr = record.get("agent_address")
            lease_id = record.get("lease_id")
            if no_restart:
                record["state"] = ActorState.DEAD
                record["death_cause"] = reason
        if worker_addr:
            try:
                self._workers.get(worker_addr).call_oneway("exit_worker")
            except RpcError:
                pass
            self._workers.drop(worker_addr)
        if agent_addr and lease_id:
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease_id, kill=True
                )
            except RpcError:
                pass
        if no_restart:
            self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
            self.publish("actor", self._public_actor(actor_id))
        else:
            self._on_actor_worker_lost(actor_id, reason)

    def _on_actor_worker_lost(self, actor_id: str, reason: str) -> None:
        """Restart-or-die decision (reference gcs_actor_manager.cc:1477)."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return
            max_restarts = record.get("max_restarts", 0)
            if max_restarts == -1 or record["num_restarts"] < max_restarts:
                record["num_restarts"] += 1
                record["state"] = ActorState.RESTARTING
                record["worker_address"] = None
                record["node_id"] = None
                restart = True
            else:
                record["state"] = ActorState.DEAD
                record["death_cause"] = reason
                restart = False
        self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
        self.publish("actor", self._public_actor(actor_id))
        if restart:
            self._sched_enqueue(("actor", actor_id))

    def _public_actor(self, actor_id: str) -> Dict[str, Any]:
        r = self._actors[actor_id]
        return {
            "actor_id": actor_id,
            "state": r["state"],
            "node_id": r.get("node_id"),
            "worker_address": r.get("worker_address"),
            "name": r.get("name"),
            "namespace": r.get("namespace", "default"),
            "class_name": r.get("class_name"),
            "method_names": r.get("method_names", []),
            "num_restarts": r.get("num_restarts", 0),
            "max_restarts": r.get("max_restarts", 0),
            "max_task_retries": r.get("max_task_retries", 0),
            "death_cause": r.get("death_cause"),
            "job_id": r.get("job_id"),
            "lifetime": r.get("lifetime"),
        }

    # ------------------------------------------------------------------
    # placement groups (reference C3: 2PC prepare/commit)
    # ------------------------------------------------------------------

    def rpc_create_placement_group(self, conn, pg_id: str, bundles: List[Dict[str, float]],
                                   strategy: str, name: Optional[str] = None,
                                   job_id: Optional[str] = None):
        with self._lock:
            self._pgs[pg_id] = {
                "pg_id": pg_id,
                "bundles": bundles,
                "strategy": strategy,
                "name": name,
                "job_id": job_id,
                "state": PGState.PENDING,
                # bundle index -> node_id hex
                "bundle_locations": {},
            }
        self._sched_enqueue(("pg", pg_id))
        return True

    def _schedule_pg_once(self, pg_id: str) -> bool:
        """One placement pass of a PG's missing bundles via 2PC (runs on
        the scheduler thread; infeasible/failed passes re-enqueue with
        backoff instead of parking a thread). Returns True when the caller
        should run another pass immediately (progress was made).

        Handles partial placement: only indices absent from
        bundle_locations are placed, so node-death recovery re-places the
        lost bundles while surviving bundles (and the actors in them) keep
        running — mirroring the reference GcsPlacementGroupManager's
        rescheduling of individual bundles.
        """
        key = ("pg", pg_id)
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg["state"] in (PGState.CREATED, PGState.REMOVED):
                self._sched_backoff.pop(key, None)
                return
            bundles = pg["bundles"]
            strategy = pg["strategy"]
            locations = {int(k): v for k, v in pg["bundle_locations"].items()}
            view = self._cluster_view_locked()
        missing = [i for i in range(len(bundles)) if i not in locations]
        if not missing:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None or pg["state"] == PGState.REMOVED:
                    return
                pg["state"] = PGState.CREATED
            self._sched_backoff.pop(key, None)
            self.publish(f"pg:{pg_id}", {"pg_id": pg_id, "state": PGState.CREATED})
            return
        place_view = view
        if strategy == "STRICT_SPREAD" and locations:
            survivors = set(locations.values())
            place_view = {
                nid: n for nid, n in view.items() if nid not in survivors
            }
        sub = scheduling.place_bundles(
            place_view, [bundles[i] for i in missing], strategy
        )
        if sub is None:
            self._sched_retry(("pg", pg_id), key)
            return
        placement = {missing[pos]: nid for pos, nid in sub.items()}
        # Phase 1: PREPARE on every involved agent.
        by_node: Dict[str, List[int]] = {}
        for idx, node_id in placement.items():
            by_node.setdefault(node_id, []).append(idx)
        ok = True
        for node_id, idxs in by_node.items():
            addr = view[node_id]["address"]
            try:
                res = self._agents.get(addr).call(
                    "prepare_bundles", pg_id=pg_id,
                    bundles={i: bundles[i] for i in idxs},
                )
            except RpcError:
                res = False
            if not res:
                ok = False
                break
        if not ok:
            # Roll back EVERY node in the attempted placement (by its
            # attempted indices), not just the ones that acked prepare:
            # a node whose prepare reply was lost may still hold the
            # reservation, and return_bundles on a node that never
            # prepared those indices is a no-op. Synchronous call so a
            # retried placement can't race its own rollback.
            self._rollback_bundles(view, by_node, pg_id)
            self._sched_retry(("pg", pg_id), key)
            return
        # Phase 2: COMMIT. A node that misses COMMIT would refuse
        # bundle leases forever (raylet requires state=="committed"),
        # so any commit failure rolls this placement back and retries.
        commit_ok = True
        for node_id, idxs in by_node.items():
            try:
                res = self._agents.get(view[node_id]["address"]).call(
                    "commit_bundles", pg_id=pg_id
                )
            except RpcError:
                res = False
            if not res:
                logger.warning("pg %s commit failed on %s", pg_id[:8], node_id[:8])
                commit_ok = False
        if not commit_ok:
            self._rollback_bundles(view, by_node, pg_id)
            self._sched_retry(("pg", pg_id), key)
            return
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            pg["bundle_locations"].update(placement)
        # go around once more: recompute missing (usually empty -> CREATED)
        return True

    def _rollback_bundles(
        self, view, by_node: Dict[str, List[int]], pg_id: str
    ) -> None:
        """Synchronously return the given bundle indices on each node (a
        one-way send could race a subsequent re-placement's prepare)."""
        for node_id, idxs in by_node.items():
            try:
                self._agents.get(view[node_id]["address"]).call(
                    "return_bundles", pg_id=pg_id, idxs=idxs
                )
            except RpcError:
                pass

    def rpc_get_placement_group(self, conn, pg_id: str):
        with self._lock:
            pg = self._pgs.get(pg_id)
            return dict(pg) if pg else None

    def rpc_wait_placement_group(self, conn, pg_id: str, wait_s: float = 60.0):
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    return None
                if pg["state"] in (PGState.CREATED, PGState.REMOVED):
                    return dict(pg)
            time.sleep(0.02)
        with self._lock:
            pg = self._pgs.get(pg_id)
            return dict(pg) if pg else None

    def rpc_remove_placement_group(self, conn, pg_id: str):
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            pg["state"] = PGState.REMOVED
            locations = dict(pg["bundle_locations"])
            view = self._cluster_view_locked()
        for node_id in set(locations.values()):
            node = view.get(node_id)
            if node:
                try:
                    self._agents.get(node["address"]).call_oneway(
                        "return_bundles", pg_id=pg_id
                    )
                except RpcError:
                    pass
        self.publish(f"pg:{pg_id}", {"pg_id": pg_id, "state": PGState.REMOVED})
        return True

    def rpc_list_placement_groups(self, conn):
        with self._lock:
            return [dict(pg) for pg in self._pgs.values()]

    # ------------------------------------------------------------------

    def _cluster_view_locked(self) -> Dict[str, Dict[str, Any]]:
        return {
            nid: {
                "address": n["address"],
                "resources_total": n["resources_total"],
                "resources_available": n["resources_available"],
                "labels": n.get("labels", {}),
                "alive": True,
            }
            for nid, n in self._nodes.items()
            if n["alive"]
        }
