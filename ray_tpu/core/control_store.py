"""Control store — the cluster control plane (GCS equivalent).

Parity: the reference GCS server (src/ray/gcs/gcs_server.h:96) and its
managers: node membership + health checks (GcsNodeManager,
gcs_health_check_manager.h:45), actor directory + FT scheduling
(GcsActorManager src/ray/gcs/actor/gcs_actor_manager.h:93, restart logic
gcs_actor_manager.cc:1477-1506), placement groups with 2-phase commit
(GcsPlacementGroupManager gcs_placement_group_manager.h:50, PREPARE/COMMIT
gcs_placement_group_scheduler.h:115-117), jobs (GcsJobManager), KV store
(store_client.h — in-memory here, pluggable), pubsub (src/ray/pubsub/), and
the resource-view syncer (src/ray/ray_syncer/ray_syncer.h:91 — here:
heartbeat-carried resource reports fanned out on a pubsub topic).

Runs as threads inside the head process. State is in-memory, with an
optional durable log behind it (core/ha/wal.py — the reference's
Redis-backed GCS FT mode, C14): every durable table mutation flows
through ONE choke point, ``_apply``, which dispatches to a ``_mut_*``
state-machine function and appends the fully-resolved operation to a
write-ahead log. Recovery replays snapshot+WAL through the same
functions, rebuilding byte-identical tables, then runs a bounded
*reconciliation window* in which live node agents re-attach and
re-assert their leases/bundles/workers before scheduling resumes
(tools/check_wal_choke.py statically enforces the choke point).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import scheduling
from ray_tpu.core.ha import FileBackend, HAState, write_head_address
from ray_tpu.observability import core_metrics, forensics, profiler
from ray_tpu.utils.config import config
from ray_tpu.utils import rpc
from ray_tpu.utils.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.utils.rpc import ClientPool, RpcError, RpcServer

logger = logging.getLogger(__name__)


class ActorState:
    DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
    PENDING_CREATION = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class PGState:
    PENDING = "PENDING"
    CREATED = "CREATED"
    REMOVED = "REMOVED"
    RESCHEDULING = "RESCHEDULING"


# Node-record fields the durable projection keeps — exactly the
# registration payload plus liveness. Everything else (heartbeat runtime
# state, reattach bookkeeping, arbitrary `extra` keys) is structurally
# excluded, so a new runtime field can never silently break replay
# determinism; agents re-assert runtime state during reconciliation.
_DURABLE_NODE_FIELDS = (
    "node_id", "address", "resources_total", "labels",
    "object_store_capacity", "alive",
)

# Ops applied through the choke point but NOT appended to the WAL:
# per-heartbeat runtime state whose replay would be meaningless across a
# process restart.
_VOLATILE_OPS = frozenset({"node_runtime"})

# Dispatcher pipelining (ISSUE 14): extra already-queued items one
# scheduler wakeup drains in the same pass, and the cap on async lease
# RPCs fired per batched-arrival item (each spawns a per-request handler
# thread on the target agent; the overflow parks in the retry heap).
_SCHED_DRAIN_MAX = 64
_SCHED_BATCH_FANOUT = 128


class ControlStore:
    def __init__(self, session_id: str, host: str = "127.0.0.1", port: int = 0,
                 persistence_path: Optional[str] = None):
        self.session_id = session_id
        # Durable log (reference C14: in-memory default vs Redis FT mode):
        # with a path, every durable mutation is WAL'd (snapshot at <path>,
        # log at <path>.wal) and a restarted control store rebuilds an
        # identical control plane, then reconciles with live agents.
        self._persistence_path = persistence_path or (
            str(config.control_store_persistence_path) or None
        )
        self._ha: Optional[HAState] = None
        if self._persistence_path:
            self._ha = HAState(
                FileBackend(self._persistence_path),
                compact_entries=int(config.ha_wal_compact_entries),
                fsync=bool(config.ha_wal_fsync),
                group_commit_ms=float(config.wal_group_commit_ms),
            )
        # Reconciliation window state (live failover): set by _restore when
        # previously-alive nodes were recovered from the log.
        self._recovering = False
        self._reconcile_deadline = 0.0
        # node_id -> re-attach report ({"leases": set, "bundles": {pg: set}})
        # — recorded only during the window, consumed+cleared at finalize
        self._reattached: Dict[str, Dict[str, Any]] = {}
        self._reattached_total = 0  # distinct nodes re-attached (status)
        self._server = RpcServer("control_store", host, port)
        self._server.register_instance(self)
        self._server.on_disconnect = self._handle_disconnect
        if self._ha is not None and self._ha.group_commit:
            # acked => durable under group commit: every reply waits for
            # the group holding its ops to flush (wal.py HAState.barrier)
            self._server.post_dispatch = self._ha.barrier

        self._lock = threading.RLock()
        self._kv: Dict[str, Dict[str, bytes]] = {}
        self._kv_cv = threading.Condition(self._lock)
        self._nodes: Dict[str, Dict[str, Any]] = {}  # node_id hex -> record
        self._actors: Dict[str, Dict[str, Any]] = {}  # actor_id hex -> record
        self._named_actors: Dict[Tuple[str, str], str] = {}
        self._pgs: Dict[str, Dict[str, Any]] = {}
        # woken on every PG terminal transition (CREATED/REMOVED) so
        # rpc_wait_placement_group returns the moment the 2PC finishes
        # instead of quantizing every waiter to a poll interval
        self._pg_cv = threading.Condition(self._lock)
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._next_job = 1

        # pubsub: topic -> {conn_id: conn}
        self._subs: Dict[str, Dict[int, Any]] = {}

        # Volatile KV traffic accounting (NOT durable state — survives
        # nothing, counts everything): payload bytes written into and
        # served out of the KV. The p2p collective tier's head-traffic
        # guarantee ("rendezvous only, independent of payload size") is
        # asserted against these counters (rpc_kv_stats).
        self._kv_traffic = {
            "puts": 0, "bytes_put": 0, "gets": 0, "bytes_out": 0,
        }

        # aggregate resource-view version: bumps on any node join/leave or
        # resource change (versioned sync, reference ray_syncer.h:91)
        self._view_version = 0

        self._agents = ClientPool("cs->agent")
        self._workers = ClientPool("cs->worker")
        self._stopped = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        # Metrics history + alert plane (ISSUE 15): built in start()
        # when sampling is enabled (metrics_sample_interval_s > 0 and
        # observability on); None otherwise so the RPC handlers report
        # "disabled" instead of serving empty stores.
        self._history = None
        self._alert_engine = None
        self._sampler = None

        # Scheduling queue (reference GcsActorScheduler/PG scheduler run
        # on the GCS io-service, not a thread per entity): ONE dispatcher
        # thread drains this queue; lease/create RPCs go out async and
        # their completions re-enqueue follow-up items, so thread count
        # stays flat no matter how many actors/PGs are pending.
        self._sched_q: "queue.Queue" = queue.Queue()
        self._sched_retries: List[Tuple[float, int, tuple]] = []  # heap
        self._sched_seq = itertools.count()
        self._sched_backoff: Dict[tuple, float] = {}
        self._sched_retry_lock = threading.Lock()  # heap+backoff (pg pool
        # threads and the dispatcher both retry/enqueue)
        # PG 2PC does synchronous prepare/commit RPCs; a hung agent must
        # not stall the (async) actor pipeline, so PG passes run on a
        # small fixed pool instead of the dispatcher thread.
        from concurrent.futures import ThreadPoolExecutor

        self._pg_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="cs-pg"
        )
        self._pg_running: set = set()
        # Parallel kill-drain (ISSUE 14): teardown RPCs (exit_worker +
        # release_workers) fan out across node agents on this bounded
        # pool instead of a serial per-actor loop in the handler thread.
        self._kill_pool = ThreadPoolExecutor(
            max_workers=max(1, int(config.actor_kill_fanout)),
            thread_name_prefix="cs-kill",
        )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._restore()
        self._server.start()
        write_head_address(self.address)
        self._health_thread = threading.Thread(
            target=self._health_loop, name="cs-health", daemon=True
        )
        self._health_thread.start()
        threading.Thread(
            target=self._sched_loop, name="cs-scheduler", daemon=True
        ).start()
        self._start_observability()
        # continuous sampler rides observability_enabled + profiler_hz
        # only — it is useful precisely when the history sampler is off
        profiler.maybe_start_continuous()
        if self._recovering:
            threading.Thread(
                target=self._reconcile_loop, name="cs-reconcile", daemon=True
            ).start()

    def _start_observability(self) -> None:
        """Start the metrics-history sampler (+ alert engine) on this
        head. interval<=0 or observability_enabled=0 disables the whole
        plane — no store, no thread, no per-tick scrape cost."""
        interval = float(config.metrics_sample_interval_s)
        if interval <= 0 or not bool(config.observability_enabled):
            return
        from ray_tpu.observability import alerts as alerts_mod
        from ray_tpu.observability import history as history_mod

        self._history = history_mod.MetricsHistory(
            base_step_s=interval,
            max_series=int(config.metrics_history_max_series),
        )
        on_tick = None
        if bool(config.alerts_enabled):
            self._alert_engine = alerts_mod.AlertEngine(
                alerts_mod.default_rules(), self._history
            )
            on_tick = self._alert_engine.evaluate
        self._sampler = history_mod.HistorySampler(
            self._history, self.address, self._stopped, interval,
            on_tick=on_tick,
        )
        self._sampler.start()

    def stop(self) -> None:
        self._stopped.set()
        self._pg_pool.shutdown(wait=False)
        self._kill_pool.shutdown(wait=False)
        # server down first, and the final snapshot under the store lock:
        # an in-flight handler must not append between the close's state
        # copy and its WAL truncation (the acked op would vanish). An
        # append that lands after the close is still safe — it reopens
        # the truncated WAL with seq > snapshot seq and replays.
        self._server.stop()
        if self._ha is not None:
            with self._lock:
                self._ha.close(self._durable_state_snapshot)
        self._agents.close_all()
        self._workers.close_all()

    # ------------------------------------------------------------------
    # durable log (reference C14: gcs_table_storage + store_client) —
    # THE WAL CHOKE POINT. Every mutation of the state tables (_kv,
    # _nodes, _actors, _named_actors, _pgs, _jobs, _next_job) must go
    # through _apply, which runs a _mut_* state-machine function and
    # appends the fully-resolved op to the WAL. tools/check_wal_choke.py
    # enforces this statically (tier-1).
    # ------------------------------------------------------------------

    def _apply(self, op: str, *args):
        """Sole entry point for state-table mutations. Caller must hold
        self._lock — appends are thereby totally ordered, and an inline
        compaction snapshot is consistent with the log position.

        Write-ahead ordering: the op is logged BEFORE the in-memory
        mutation runs, so an append failure (disk full, closed backend)
        surfaces to the caller with memory and log still in agreement —
        logged-but-unapplied is the one crash window, and replay then
        applies it, which is the WAL contract (logged == committed)."""
        assert self._lock._is_owned(), "mutation outside the store lock"
        if (
            self._ha is not None
            and op not in _VOLATILE_OPS
            # collective rendezvous namespaces (coll/*) are incarnation-
            # scoped: replaying them into a restarted cluster would satisfy
            # a new group's barrier/op tags with a dead run's keys.
            and not (op.startswith("kv_") and args[0].startswith("coll/"))
        ):
            self._ha.append(op, args, self._durable_state_snapshot)
        return getattr(self, "_mut_" + op)(*args)

    # -- state-machine mutation functions: pure in-memory table updates,
    # -- deterministic given their (logged) args; no RPC, no clock reads.

    def _mut_kv_put(self, ns: str, key: str, value: bytes) -> None:
        self._kv.setdefault(ns, {})[key] = value

    def _mut_kv_del(self, ns: str, key: str) -> bool:
        return self._kv.get(ns, {}).pop(key, None) is not None

    def _mut_kv_del_prefix(self, ns: str, prefix: str) -> int:
        table = self._kv.get(ns)
        if table is None:
            return 0
        doomed = [k for k in table if k.startswith(prefix)]
        for k in doomed:
            del table[k]
        if not table and prefix == "":
            self._kv.pop(ns, None)
        return len(doomed)

    def _mut_node_register(self, node_id: str, info: Dict[str, Any]) -> None:
        node = self._nodes.get(node_id)
        if node is None:
            node = {}
            self._nodes[node_id] = node
        node.update(info)
        node["alive"] = True

    def _mut_node_runtime(self, node_id: str, fields: Dict[str, Any]) -> None:
        # VOLATILE: heartbeat-carried runtime state, never WAL'd.
        node = self._nodes.get(node_id)
        if node is not None:
            node.update(fields)

    def _mut_node_dead(self, node_id: str) -> None:
        node = self._nodes.get(node_id)
        if node is not None:
            node["alive"] = False

    def _mut_job_add(self, driver_address: str, metadata: Dict[str, Any],
                     ts: float) -> str:
        job_id = JobID.from_int(self._next_job)
        self._next_job += 1
        self._jobs[job_id.hex()] = {
            "job_id": job_id.hex(),
            "driver_address": driver_address,
            "metadata": metadata,
            "start_time": ts,
            "alive": True,
        }
        return job_id.hex()

    def _mut_job_finish(self, job_id: str, ts: float) -> None:
        job = self._jobs.get(job_id)
        if job:
            job["alive"] = False
            job["end_time"] = ts

    def _mut_actor_register(self, record: Dict[str, Any]) -> None:
        actor_id = record["actor_id"]
        self._actors[actor_id] = dict(record)
        name = record.get("name")
        if name:
            self._named_actors[(record.get("namespace", "default"), name)] = (
                actor_id
            )

    def _mut_actor_update(self, actor_id: str, fields: Dict[str, Any]) -> None:
        record = self._actors.get(actor_id)
        if record is not None:
            record.update(fields)

    def _mut_pg_add(self, record: Dict[str, Any]) -> None:
        rec = dict(record)
        rec["bundle_locations"] = dict(rec.get("bundle_locations") or {})
        self._pgs[rec["pg_id"]] = rec

    def _mut_pg_update(self, pg_id: str, fields: Dict[str, Any]) -> None:
        pg = self._pgs.get(pg_id)
        if pg is not None:
            pg.update(fields)

    def _mut_pg_merge_locations(self, pg_id: str,
                                placement: Dict[int, str]) -> None:
        pg = self._pgs.get(pg_id)
        if pg is not None:
            pg["bundle_locations"].update(
                {int(i): nid for i, nid in placement.items()}
            )

    def _mut_pg_drop_locations(self, pg_id: str, idxs: List[int]) -> None:
        pg = self._pgs.get(pg_id)
        if pg is not None:
            for i in idxs:
                pg["bundle_locations"].pop(int(i), None)

    # -- durable projection + snapshot/restore --

    def _durable_state(self) -> Dict[str, Any]:
        """The WAL-covered tables, minus volatile runtime fields. Replay
        of snapshot+WAL reproduces this projection byte-identically
        (tests/test_ha_failover.py::test_wal_replay_determinism)."""
        return {
            "kv": {
                ns: dict(t) for ns, t in self._kv.items()
                if not ns.startswith("coll/")
            },
            "nodes": {
                nid: {k: n[k] for k in _DURABLE_NODE_FIELDS if k in n}
                for nid, n in self._nodes.items()
            },
            "jobs": {j: dict(r) for j, r in self._jobs.items()},
            "next_job": self._next_job,
            "actors": {a: dict(r) for a, r in self._actors.items()},
            "named_actors": dict(self._named_actors),
            "pgs": {
                p: dict(r, bundle_locations=dict(r["bundle_locations"]))
                for p, r in self._pgs.items()
            },
        }

    def _durable_state_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return self._durable_state()

    def _load_tables(self, tables: Dict[str, Any]) -> None:
        self._kv = {ns: dict(t) for ns, t in tables.get("kv", {}).items()}
        self._nodes = {n: dict(r) for n, r in tables.get("nodes", {}).items()}
        self._jobs = {j: dict(r) for j, r in tables.get("jobs", {}).items()}
        self._next_job = tables.get("next_job", 1)
        self._actors = {
            a: dict(r) for a, r in tables.get("actors", {}).items()
        }
        self._named_actors = dict(tables.get("named_actors", {}))
        self._pgs = {
            p: dict(r, bundle_locations=dict(r["bundle_locations"]))
            for p, r in tables.get("pgs", {}).items()
        }

    def _restore(self) -> None:
        if self._ha is None:
            return
        tables, records = self._ha.recover()
        if tables is None and not records:
            self._ha.start(
                self._durable_state_snapshot,
                meta={"session_id": self.session_id},
            )
            return
        prev_session = self._ha.meta.get("session_id")
        with self._lock:
            if tables is not None:
                self._load_tables(tables)
            for op, args in records:
                try:
                    getattr(self, "_mut_" + op)(*args)
                except Exception:  # noqa: BLE001 — replay must not abort
                    logger.exception("WAL replay of %s%r failed", op, args)
            self._post_restore_locked()
        if prev_session:
            # keep the cluster's session identity stable across the bounce
            # (agents/workers key temp dirs and shm prefixes by it)
            self.session_id = prev_session
        self._ha.start(
            self._durable_state_snapshot,
            meta={"session_id": self.session_id},
        )
        logger.info(
            "control store restored (epoch %d): %d nodes, %d actors, "
            "%d PGs, %d jobs, %d KV namespaces; %s",
            self._ha.epoch, len(self._nodes), len(self._actors),
            len(self._pgs), len(self._jobs), len(self._kv),
            "reconciliation window open" if self._recovering
            else "no live nodes to reconcile",
        )

    def _post_restore_locked(self) -> None:
        """Reset volatile runtime state after a replay: node liveness is
        re-asserted by the agents themselves during the reconciliation
        window; monotonic stamps from the dead process are meaningless."""
        now = time.monotonic()
        restored_alive = []
        for nid in self._nodes:
            self._apply("node_runtime", nid, {
                "last_heartbeat": now,
                "resources_available": dict(
                    self._nodes[nid].get("resources_total", {})
                ),
                "reconciled": False,
            })
            if self._nodes[nid].get("alive"):
                restored_alive.append(nid)
        self._view_version += 1
        if restored_alive:
            self._recovering = True
            self._reconcile_deadline = now + float(
                config.ha_reconcile_window_s
            )
        # nothing in-flight survives a restart: requeue pending work (the
        # scheduler defers it until the reconciliation window closes)
        for aid, r in self._actors.items():
            if r["state"] in (
                ActorState.PENDING_CREATION, ActorState.RESTARTING,
            ):
                self._sched_enqueue(("actor", aid))
        for pid, pg in self._pgs.items():
            if pg["state"] in (PGState.PENDING, PGState.RESCHEDULING):
                self._sched_enqueue(("pg", pid))

    # -- reconciliation window (live failover) --

    def _reconcile_loop(self) -> None:
        while not self._stopped.wait(0.1):
            with self._lock:
                if not self._recovering:
                    return
                pending = [
                    nid for nid, n in self._nodes.items()
                    if n.get("alive") and not n.get("reconciled")
                ]
                if pending and time.monotonic() < self._reconcile_deadline:
                    continue
            self._finalize_reconciliation()
            return

    def _finalize_reconciliation(self) -> None:
        with self._lock:
            # compute the stale set in the same critical section that ends
            # the window: a node whose reattach lands after this point is
            # spared again inside _mark_node_dead's reconciled re-check —
            # a live, successfully re-attached node must never be GC'd
            self._recovering = False
            stale_nodes = [
                nid for nid, n in self._nodes.items()
                if n.get("alive") and not n.get("reconciled")
            ]
        for nid in stale_nodes:
            logger.warning(
                "node %s did not re-attach within the reconciliation "
                "window; garbage-collecting", nid[:8],
            )
            self._mark_node_dead(
                nid, "did not re-attach after head restart",
                only_if_unreconciled=True,
            )
        # Verify restored-ALIVE actors against the agents' re-attach
        # reports: a worker that died during the outage never told us.
        lost = []
        with self._lock:
            for aid, r in self._actors.items():
                if r["state"] != ActorState.ALIVE:
                    continue
                nid = r.get("node_id")
                node = self._nodes.get(nid) if nid else None
                if node is None or not node["alive"]:
                    continue  # _mark_node_dead above already failed it over
                report = self._reattached.get(nid)
                if report is None:
                    # alive node without a report: its reattach raced the
                    # window close (recorded nothing) — SPARE the actor;
                    # killing a possibly-running instance risks split
                    # brain, and a genuinely dead worker is still caught
                    # by the agent's report_worker_failure path
                    continue
                if r.get("lease_id") not in report["leases"]:
                    lost.append(aid)
        for aid in lost:
            self._on_actor_worker_lost(aid, "worker lost during head outage")
        # Verify PG bundle placements the same way, then resume pending
        # placement work.
        requeue_pgs = []
        with self._lock:
            for pg in self._pgs.values():
                if pg["state"] not in (PGState.CREATED, PGState.PENDING,
                                       PGState.RESCHEDULING):
                    continue
                drop = []
                for idx, nid in list(pg["bundle_locations"].items()):
                    node = self._nodes.get(nid)
                    if node is None or not node["alive"]:
                        drop.append(idx)
                        continue
                    report = self._reattached.get(nid)
                    if report is not None and idx not in report[
                        "bundles"
                    ].get(pg["pg_id"], ()):
                        drop.append(idx)
                if drop:
                    self._apply("pg_drop_locations", pg["pg_id"], drop)
                    if pg["state"] == PGState.CREATED:
                        self._apply(
                            "pg_update", pg["pg_id"],
                            {"state": PGState.PENDING},
                        )
                if pg["state"] in (PGState.PENDING, PGState.RESCHEDULING):
                    requeue_pgs.append(pg["pg_id"])
        for pid in requeue_pgs:
            self._sched_enqueue(("pg", pid))
        self._sched_enqueue(("kick",))
        with self._lock:
            reattached = len(self._reattached)
            self._reattached.clear()  # reports are consumed; window over
        self.publish("head", {"event": "reconciled",
                              "stale_nodes": stale_nodes})
        logger.info(
            "reconciliation complete: %d nodes re-attached, %d stale "
            "nodes GC'd, %d actors failed over, %d PGs re-placing",
            reattached, len(stale_nodes), len(lost), len(requeue_pgs),
        )

    def rpc_reattach_node(self, conn, node_info: Dict[str, Any],
                          leases: Optional[Dict[str, Dict[str, Any]]] = None,
                          bundles: Optional[Dict[str, List[int]]] = None,
                          workers: Optional[List[str]] = None):
        """A live agent re-asserts its state after a head restart (or
        after the store otherwise lost its registration). Returns the
        normal registration payload plus store-managed lease_ids the
        agent should release (orphans no live actor references)."""
        node_id = node_info["node_id"]
        leases = leases or {}
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None and not node["alive"]:
                return {"ok": False}  # explicitly declared dead: agent exits
            known = node is not None
            self._apply("node_register", node_id, dict(node_info))
            self._apply("node_runtime", node_id, {
                "last_heartbeat": time.monotonic(),
                "resources_available": dict(node_info["resources_total"]),
                "reconciled": True,
            })
            self._view_version += 1
            if self._recovering:
                # the report only feeds _finalize_reconciliation; post-
                # window reattaches (store lost a record) must not
                # accumulate in it forever
                if node_id not in self._reattached:
                    self._reattached_total += 1
                self._reattached[node_id] = {
                    "leases": set(leases),
                    "bundles": {
                        pg_id: {int(i) for i in idxs}
                        for pg_id, idxs in (bundles or {}).items()
                    },
                    "workers": list(workers or ()),
                }
            referenced = {
                r.get("lease_id") for r in self._actors.values()
                if r["state"] in (ActorState.ALIVE,
                                  ActorState.PENDING_CREATION)
            }
            release = [
                lid for lid, info in leases.items()
                if not info.get("bound") and lid not in referenced
            ]
        logger.info(
            "node %s re-attached (%d leases, %d PGs, %d workers; "
            "%d orphan leases to release)",
            node_id[:8], len(leases), len(bundles or {}),
            len(workers or ()), len(release),
        )
        if not known:
            self.publish(
                "node", {"event": "added", "node": self._public_node(node_id)}
            )
        self._sched_enqueue(("kick",))
        return {
            "ok": True,
            "config_snapshot": config.snapshot(),
            "session_id": self.session_id,
            "release_leases": release,
        }

    def rpc_ha_status(self, conn):
        """HA/failover introspection for `rt status` and tests."""
        with self._lock:
            st: Dict[str, Any] = {
                "enabled": self._ha is not None,
                "recovering": self._recovering,
                "reconcile_remaining_s": (
                    max(0.0, self._reconcile_deadline - time.monotonic())
                    if self._recovering else 0.0
                ),
                "unreconciled_nodes": [
                    nid for nid, n in self._nodes.items()
                    if n.get("alive") and not n.get("reconciled", True)
                ],
                "reattached_nodes": self._reattached_total,
            }
            if self._ha is not None:
                st.update(self._ha.stats())
        return st

    @property
    def address(self) -> str:
        return self._server.address

    # ------------------------------------------------------------------
    # pubsub (reference C16)
    # ------------------------------------------------------------------

    def rpc_subscribe(self, conn, topics: List[str]):
        with self._lock:
            for t in topics:
                self._subs.setdefault(t, {})[id(conn)] = conn
        return True

    def rpc_publish(self, conn, topic: str, payload: Any):
        self.publish(topic, payload)
        return True

    def publish(self, topic: str, payload: Any) -> None:
        with self._lock:
            conns = list(self._subs.get(topic, {}).values())
        if not conns:
            return
        # serialize ONCE per publish; the encoded frame is shared (read-
        # only) across every subscriber connection instead of re-pickling
        # the payload per subscriber
        bufs = rpc.encode_message(("push", "pubsub", (topic, payload)))
        for c in conns:
            if not c.push_encoded(bufs):
                with self._lock:
                    self._subs.get(topic, {}).pop(id(c), None)

    def _handle_disconnect(self, conn) -> None:
        with self._lock:
            for subs in self._subs.values():
                subs.pop(id(conn), None)
        node_id = getattr(conn, "node_id", None)
        if node_id is not None:
            # Fast failure detection: the agent's heartbeat connection
            # broke. Confirm with a short grace (a live agent re-heartbeats
            # on a fresh connection within one period) before declaring
            # death — much faster than the full health_check_timeout_s.
            threading.Thread(
                target=self._confirm_node_death, args=(node_id,),
                name="cs-conn-death", daemon=True,
            ).start()

    def _confirm_node_death(self, node_id: str) -> None:
        if self._recovering:
            return  # mid-reattach churn must not kill a returning node
        t_break = time.monotonic()
        grace = 2.5 * config.health_check_period_s
        while time.monotonic() - t_break < grace:
            if self._stopped.wait(0.25):
                return
            with self._lock:
                node = self._nodes.get(node_id)
                if node is None or not node["alive"]:
                    return
                if node["last_heartbeat"] > t_break:
                    return  # re-heartbeated on a fresh connection: alive
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"] or (
                node["last_heartbeat"] > t_break
            ):
                return
        logger.warning(
            "node %s heartbeat connection lost; marking dead", node_id[:8]
        )
        self._mark_node_dead(node_id, "heartbeat connection lost")

    # ------------------------------------------------------------------
    # KV (reference C14 / internal KV)
    # ------------------------------------------------------------------

    def rpc_kv_put(self, conn, ns: str, key: str, value: bytes, overwrite: bool = True):
        with self._lock:
            if not overwrite and key in self._kv.get(ns, {}):
                return False
            self._kv_traffic["puts"] += 1
            self._kv_traffic["bytes_put"] += len(value) if value is not None else 0
            self._apply("kv_put", ns, key, value)
            self._kv_cv.notify_all()
            return True

    def _kv_note_out(self, val) -> None:
        """Count a KV value served to a client (volatile accounting)."""
        if val is not None:
            self._kv_traffic["gets"] += 1
            self._kv_traffic["bytes_out"] += len(val)

    def rpc_kv_get(self, conn, ns: str, key: str):
        with self._lock:
            val = self._kv.get(ns, {}).get(key)
            self._kv_note_out(val)
            return val

    def rpc_kv_wait(self, conn, ns: str, key: str, wait_s: float = 60.0):
        """Block server-side until the key exists (or timeout); returns
        the value or None. The collective tier's rendezvous primitive:
        one blocking RPC replaces a client-side poll loop (the round-2
        O(n^2)-polling weakness).

        The server never honors the caller's full deadline in one call:
        the wait is capped at dispatch_wait_slice_s so a fan-in of
        blocked waiters can't strand the whole dispatcher pool (clients
        re-issue slices until their own deadline — see
        collective._recv_either)."""
        wait_s = min(wait_s, float(config.dispatch_wait_slice_s))
        deadline = time.monotonic() + wait_s
        with self._lock:
            while True:
                val = self._kv.get(ns, {}).get(key)
                if val is not None:
                    self._kv_note_out(val)
                    return val
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopped.is_set():
                    return None
                self._kv_cv.wait(min(remaining, 1.0))

    def rpc_kv_stats(self, conn):
        """Volatile KV traffic counters: payload bytes in (kv_put) and
        out (kv_get/kv_wait hits) since this head process started. Tests
        pin the collective head-traffic guarantee against deltas of
        these."""
        with self._lock:
            return dict(self._kv_traffic)

    def rpc_kv_del(self, conn, ns: str, key: str):
        with self._lock:
            if key not in self._kv.get(ns, {}):
                return False
            return self._apply("kv_del", ns, key)

    def rpc_kv_keys(self, conn, ns: str, prefix: str = ""):
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    def rpc_kv_del_prefix(self, conn, ns: str, prefix: str = ""):
        with self._lock:
            if not any(
                k.startswith(prefix) for k in self._kv.get(ns, ())
            ):
                return 0
            return self._apply("kv_del_prefix", ns, prefix)

    # ------------------------------------------------------------------
    # nodes (reference GcsNodeManager + health checks + syncer)
    # ------------------------------------------------------------------

    def rpc_register_node(self, conn, node_info: Dict[str, Any]):
        node_id = node_info["node_id"]
        with self._lock:
            self._apply("node_register", node_id, dict(node_info))
            self._apply("node_runtime", node_id, {
                "last_heartbeat": time.monotonic(),
                "resources_available": dict(node_info["resources_total"]),
                "reconciled": True,
            })
            self._view_version += 1
        logger.info("node %s registered at %s", node_id[:8], node_info["address"])
        self.publish("node", {"event": "added", "node": self._public_node(node_id)})
        # fresh capacity: retry anything the scheduler had parked
        self._sched_enqueue(("kick",))
        return {"config_snapshot": config.snapshot(), "session_id": self.session_id}

    def rpc_heartbeat(self, conn, node_id: str,
                      resources_available: Optional[Dict[str, float]] = None,
                      extra: Optional[Dict[str, Any]] = None,
                      pending_leases: int = 0, active_leases: int = 0,
                      view_version: Optional[int] = None):
        """Versioned resource-view sync (reference ray_syncer.h:91):
        resources_available=None is a LIGHT beat — liveness only, the
        resource view is unchanged at `view_version`. A version mismatch
        (store restarted / payload lost) asks the agent to resync with a
        full beat."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                # The store has no record of this live agent (restarted
                # head with no/lost log): ask it to re-attach rather than
                # telling it to die.
                return {"ok": False, "reattach": True}
            if not node["alive"]:
                return {"ok": False}  # tells a zombie agent to exit
            # Tag the transport so a broken agent connection fast-paths
            # failure detection (reference: GCS treats the raylet channel
            # break as a death signal, not just missed heartbeats).
            conn.node_id = node_id
            # Heartbeats call _mut_node_runtime DIRECTLY instead of going
            # through _apply: node_runtime is in _VOLATILE_OPS (never
            # WAL'd), so the choke point adds only dispatch overhead on
            # the store's single hottest path — one call per node per
            # beat. Cold-path node_runtime writers (register/reattach/
            # restore) keep using _apply.
            if not node.get("reconciled", True):
                # restored-from-log record: the agent must re-assert its
                # leases/bundles/workers before scheduling trusts the node
                self._mut_node_runtime(node_id, {"last_heartbeat": time.monotonic()})  # rtlint: ignore[wal-choke] volatile heartbeat field, _VOLATILE_OPS skips the WAL; hot path bypasses _apply dispatch
                return {"ok": True, "reattach": True}
            runtime: Dict[str, Any] = {"last_heartbeat": time.monotonic()}
            if resources_available is None:
                self._mut_node_runtime(node_id, runtime)  # rtlint: ignore[wal-choke] volatile heartbeat field, _VOLATILE_OPS skips the WAL; hot path bypasses _apply dispatch
                if node.get("view_version") != view_version:
                    return {"ok": True, "resync": True}
                return {"ok": True}
            runtime.update({
                "resources_available": resources_available,
                "pending_leases": pending_leases,
                "active_leases": active_leases,
                "view_version": view_version,
            })
            if extra:
                runtime.update(extra)
            self._mut_node_runtime(node_id, runtime)  # rtlint: ignore[wal-choke] volatile heartbeat runtime, _VOLATILE_OPS skips the WAL; hot path bypasses _apply dispatch
            self._view_version += 1
        return {"ok": True}

    def rpc_capacity_freed(self, conn, node_id: str):
        """A lease was released on `node_id`: retry parked scheduling work
        immediately instead of waiting out its backoff (ADVICE r4: pending
        actors otherwise idle up to 2s after capacity frees). Coalesced:
        on a busy cluster every release fires this, so kicks within 100ms
        collapse to one — a dropped kick only costs one short backoff step
        (heartbeat anti-entropy is the backstop)."""
        now = time.monotonic()
        if now - getattr(self, "_last_kick_req", 0.0) >= 0.1:
            self._last_kick_req = now
            self._sched_enqueue(("kick",))
        return {"ok": True}

    def rpc_get_nodes(self, conn, alive_only: bool = True):
        with self._lock:
            return [
                self._public_node(nid)
                for nid, n in self._nodes.items()
                if n["alive"] or not alive_only
            ]

    def rpc_get_cluster_view(self, conn, known_version: Optional[int] = None):
        """Scheduling view: per-node totals/availables (syncer
        equivalent). With known_version, reply {"unchanged": True} when
        the aggregate view hasn't moved — consumers polling the view
        (autoscaler, elastic train) pay O(1) instead of O(nodes)."""
        with self._lock:
            if known_version is not None:
                if known_version == self._view_version:
                    return {"unchanged": True, "version": self._view_version}
                return {
                    "version": self._view_version,
                    "view": self._cluster_view_locked(),
                }
            return self._cluster_view_locked()

    def rpc_drain_node(self, conn, node_id: str):
        self._mark_node_dead(node_id, "drained")
        return True

    def rpc_get_metrics(self, conn):
        """This process's metric registry (built-in scheduler series live
        here). The token lets state.cluster_metrics dedup the head case
        where control store + agent + driver share one process."""
        from ray_tpu.utils import metrics as metrics_mod

        return {
            "token": metrics_mod.PROCESS_TOKEN,
            "metrics": metrics_mod.snapshot_all(),
        }

    def rpc_metrics_history(self, conn, name: Optional[str] = None,
                            tags: Optional[Dict[str, str]] = None,
                            window_s: Optional[float] = None,
                            step_s: Optional[float] = None):
        """Query the head-side metrics history (observability/history.py).
        name=None returns the store inventory + sampler stats; with a
        name, aggregated points for that metric (tags filter, window,
        requested resolution)."""
        h = self._history
        if h is None:
            return {"enabled": False}
        if name is None:
            return {"enabled": True, **h.stats()}
        out = h.query(name, tags=tags, window_s=window_s, step_s=step_s)
        out["enabled"] = True
        return out

    def rpc_alerts(self, conn):
        """Current alert-rule states (observability/alerts.py)."""
        eng = self._alert_engine
        if eng is None:
            return {"enabled": False, "alerts": []}
        return {"enabled": True, "alerts": eng.describe()}

    def rpc_profile(self, conn, duration_s: float = 5.0,
                    hz: float = 99.0):
        """Sample the head process's threads. The caller-supplied
        duration is capped so a profile RPC can hold a dispatcher
        thread for at most profiler_max_duration_s."""
        duration_s = min(
            float(duration_s), float(config.profiler_max_duration_s)
        )
        return profiler.capture(duration_s=duration_s, hz=hz)

    def rpc_stack_dump(self, conn):
        """All-thread stacks from the head process (hang forensics)."""
        return forensics.all_thread_stacks()

    def _public_node(self, node_id: str) -> Dict[str, Any]:
        n = self._nodes[node_id]
        return {
            "node_id": node_id,
            "address": n["address"],
            "resources_total": n["resources_total"],
            "labels": n.get("labels", {}),
            "alive": n["alive"],
            "pending_leases": n.get("pending_leases", 0),
            "active_leases": n.get("active_leases", 0),
            "pending_shapes": n.get("pending_shapes", []),
        }

    def _health_loop(self) -> None:
        while not self._stopped.wait(config.health_check_period_s):
            if self._recovering:
                continue  # reconciliation window: agents get time to return
            now = time.monotonic()
            dead = []
            with self._lock:
                for nid, n in self._nodes.items():
                    if n["alive"] and now - n["last_heartbeat"] > config.health_check_timeout_s:
                        dead.append(nid)
                n_dead = sum(
                    1 for n in self._nodes.values() if not n["alive"]
                ) + len(dead)
            if core_metrics.ENABLED:
                core_metrics.cluster_nodes_dead.set(float(n_dead))
            for nid in dead:
                logger.warning("node %s missed heartbeats; marking dead", nid[:8])
                self._mark_node_dead(nid, "heartbeat timeout")

    def _mark_node_dead(self, node_id: str, reason: str,
                        only_if_unreconciled: bool = False) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node["alive"]:
                return
            if only_if_unreconciled and node.get("reconciled", True):
                return  # re-attached between the stale scan and this call
            self._apply("node_dead", node_id)
            self._view_version += 1
            affected_actors = [
                a["actor_id"] for a in self._actors.values()
                if a.get("node_id") == node_id
                and a["state"] in (ActorState.ALIVE, ActorState.PENDING_CREATION)
            ]
            # PGs with a bundle on the dead node drop ONLY the lost bundle
            # locations and go back to PENDING for partial re-placement
            # (reference: GcsPlacementGroupManager reschedules on node
            # death); survivors' bundles — and the actors in them — keep
            # running. Without this, leases against the PG fail forever
            # with "bundle not found".
            replaced_pgs = []
            for pg in self._pgs.values():
                if pg["state"] != PGState.CREATED:
                    continue
                lost = [
                    i for i, nid in pg["bundle_locations"].items()
                    if nid == node_id
                ]
                if lost:
                    self._apply("pg_drop_locations", pg["pg_id"], lost)
                    self._apply(
                        "pg_update", pg["pg_id"], {"state": PGState.PENDING}
                    )
                    replaced_pgs.append(pg["pg_id"])
        self.publish("node", {"event": "removed", "node_id": node_id, "reason": reason})
        for actor_id in affected_actors:
            self._on_actor_worker_lost(actor_id, f"node died: {reason}")
        for pg_id in replaced_pgs:
            self._sched_enqueue(("pg", pg_id))

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------

    def rpc_register_job(self, conn, driver_address: str, metadata: Dict[str, Any]):
        with self._lock:
            return self._apply("job_add", driver_address, metadata, time.time())

    def rpc_finish_job(self, conn, job_id: str):
        with self._lock:
            if job_id in self._jobs:
                self._apply("job_finish", job_id, time.time())
        # Non-detached actors owned by the job die with it.
        with self._lock:
            doomed = [
                a["actor_id"] for a in self._actors.values()
                if a.get("job_id") == job_id
                and a.get("lifetime") != "detached"
                and a["state"] not in (ActorState.DEAD,)
            ]
        self._kill_actors_internal(doomed, "job finished", no_restart=True)
        return True

    def rpc_list_jobs(self, conn):
        with self._lock:
            return list(self._jobs.values())

    # ------------------------------------------------------------------
    # actors (reference C2: GcsActorManager + GcsActorScheduler)
    # ------------------------------------------------------------------

    def rpc_register_actor(self, conn, spec: Dict[str, Any]):
        """Register + asynchronously schedule an actor.

        spec: actor_id, job_id, class_blob_key, init args (by value or refs),
        resources, name/namespace, lifetime, max_restarts, max_concurrency,
        scheduling_strategy, owner_address.
        """
        with self._lock:
            err = self._register_actor_locked(spec)
        if err is not None:
            raise ValueError(err)
        self._sched_enqueue(("actor", spec["actor_id"]))
        return True

    def rpc_register_actors(self, conn, specs: List[Dict[str, Any]]):
        """Bulk registration (ISSUE 14): one RPC + ONE dispatcher wakeup
        for a whole batch of actor specs. Results are per-record — a bad
        spec (e.g. name conflict) reports its error without poisoning its
        siblings. Each record still logs an individual `actor_register`
        WAL op through _apply, so replay is identical whether specs
        arrived batched or one at a time."""
        results: List[Dict[str, Any]] = []
        accepted: List[str] = []
        with self._lock:
            for spec in specs:
                try:
                    err = self._register_actor_locked(spec)
                except Exception as e:  # noqa: BLE001 — malformed spec
                    err = f"{type(e).__name__}: {e}"
                if err is None:
                    accepted.append(spec["actor_id"])
                    results.append({"actor_id": spec.get("actor_id"), "ok": True})
                else:
                    results.append({
                        "actor_id": spec.get("actor_id"), "ok": False,
                        "error": err,
                    })
        if accepted:
            self._sched_enqueue(("actors", accepted))
        return results

    def _register_actor_locked(self, spec: Dict[str, Any]) -> Optional[str]:
        """Validate + apply one registration under the store lock. Returns
        an error string (None = registered). Re-registering an existing
        actor_id is idempotent-ok, so a retried batch cannot fail on the
        records its first attempt already landed."""
        actor_id = spec["actor_id"]
        if actor_id in self._actors:
            return None  # duplicate delivery of a retried batch
        name = spec.get("name")
        ns = spec.get("namespace", "default")
        if name:
            key = (ns, name)
            if key in self._named_actors:
                existing = self._named_actors[key]
                if self._actors[existing]["state"] != ActorState.DEAD:
                    return (
                        f"actor name {name!r} already taken in namespace {ns!r}"
                    )
        record = {
            **spec,
            "state": ActorState.PENDING_CREATION,
            "num_restarts": 0,
            "node_id": None,
            "worker_address": None,
            "death_cause": None,
        }
        self._apply("actor_register", record)
        return None

    # -- scheduling queue (reference: GcsActorScheduler + PG scheduler on
    # -- the GCS io-service; one dispatcher, async RPC continuations) ----

    def _sched_enqueue(self, item: tuple) -> None:
        # queue entries carry their enqueue time so the dispatcher can
        # report queue-wait (rt_sched_dispatch_latency_s) — the "which
        # queue is the bottleneck" signal at pod scale
        self._sched_q.put((time.monotonic(), item))
        if core_metrics.ENABLED:
            core_metrics.sched_queue_depth.set(self._sched_q.qsize())

    def _sched_retry(self, item: tuple, key: tuple) -> None:
        """Re-enqueue after this key's (exponential, capped) backoff.
        The 10s cap is a background anti-entropy poll, not the wake-up
        path: capacity_freed kicks requeue parked items the moment a
        lease frees, so thousands of unplaceable actors idle at ~0.1
        pass/s each instead of hammering the dispatcher at the old 2s
        cap (0.5 pass/s x 2000 pending saturated it)."""
        with self._sched_retry_lock:
            backoff = self._sched_backoff.get(key, 0.05)
            self._sched_backoff[key] = min(backoff * 2, 10.0)
            heapq.heappush(
                self._sched_retries,
                (time.monotonic() + backoff, next(self._sched_seq), item),
            )

    def _sched_kick(self) -> None:
        """Cluster capacity changed (node joined / lease freed / worker
        spawned): retry everything now, and reset the kicked keys' backoff
        so a retry that races the freed capacity (e.g. replacement worker
        still booting) re-polls at 50ms instead of the 2s cap."""
        with self._sched_retry_lock:
            items = [it for _, _, it in self._sched_retries]
            self._sched_retries.clear()
            for it in items:
                # HALVE (not clear) the backoff: the kick itself is the
                # immediate retry, and a later capacity event kicks again
                # — but a permanently-unplaceable item on a high-churn
                # cluster must keep re-climbing toward the cap instead of
                # running a full placement pass per kick at the 50ms floor
                key = tuple(it[:2])
                if key in self._sched_backoff:
                    self._sched_backoff[key] = max(
                        0.05, self._sched_backoff[key] / 2
                    )
        for it in items:
            self._sched_enqueue(it)

    def _sched_purge(self, keys: set) -> None:
        """Drop parked retry entries (and backoff state) for keys whose
        entities just died. Without this a bulk kill leaves thousands of
        dead actors' entries in the retry heap, and every subsequent
        capacity kick (each lease grant/release fires one) re-enqueues
        the whole pile — unrelated work (e.g. a PG bench right after a
        kill drain) then queues FIFO behind hundreds of thousands of
        no-op placement passes."""
        with self._sched_retry_lock:
            if self._sched_retries:
                kept = [
                    e for e in self._sched_retries
                    if tuple(e[2][:2]) not in keys
                ]
                if len(kept) != len(self._sched_retries):
                    self._sched_retries[:] = kept
                    heapq.heapify(self._sched_retries)
            for key in keys:
                self._sched_backoff.pop(key, None)

    def _sched_loop(self) -> None:
        while not self._stopped.is_set():
            now = time.monotonic()
            ready = []
            with self._sched_retry_lock:
                while self._sched_retries and self._sched_retries[0][0] <= now:
                    _, _, item = heapq.heappop(self._sched_retries)
                    ready.append(item)
                timeout = 0.5
                if self._sched_retries:
                    timeout = min(timeout, self._sched_retries[0][0] - now)
            for item in ready:
                self._sched_enqueue(item)
            try:
                enq_ts, item = self._sched_q.get(timeout=max(timeout, 0.005))
            except queue.Empty:
                continue
            # Pipelined drain (ISSUE 14): take everything already queued
            # in the same pass instead of one wakeup per item — under a
            # burst (bulk register, mass kill) the per-wakeup overhead
            # (metrics, retry-heap scan, queue round trip) amortizes over
            # the burst instead of multiplying with it.
            batch = [(enq_ts, item)]
            while len(batch) < _SCHED_DRAIN_MAX:
                try:
                    batch.append(self._sched_q.get_nowait())
                except queue.Empty:
                    break
            if core_metrics.ENABLED:
                core_metrics.sched_queue_depth.set(self._sched_q.qsize())
                now = time.monotonic()
                for enq_ts, item in batch:
                    core_metrics.sched_dispatch_latency_s.observe(
                        now - enq_ts, tags={"kind": str(item[0])}
                    )
            for _, item in batch:
                try:
                    self._process_sched(item)
                except Exception:  # noqa: BLE001 — scheduler must survive
                    logger.exception("scheduler item %r failed", item)
                    # never DROP a pending entity on a scheduling crash:
                    # retry with the key's backoff (capped), so a transient
                    # error (node died mid-pass) can't orphan an actor/PG
                    if item and item[0] in ("actor", "pg"):
                        self._sched_retry(item, tuple(item[:2]))
                    elif item and item[0] == "actors":
                        for aid in item[1]:
                            self._sched_retry(("actor", aid), ("actor", aid))

    def _process_sched(self, item: tuple) -> None:
        kind = item[0]
        if self._recovering and kind in ("actor", "pg", "actors"):
            # reconciliation window: placement decisions wait until live
            # agents have re-asserted their leases/bundles — scheduling
            # against a half-reconciled view would double-place actors
            if kind == "actors":
                for aid in item[1]:
                    self._sched_retry(("actor", aid), ("actor", aid))
            else:
                self._sched_retry(item, tuple(item[:2]))
            return
        if kind == "actor":
            self._sched_actor_place(item[1])
        elif kind == "actors":
            # batched arrival (rpc_register_actors): one wakeup schedules
            # the whole batch. Cap the async lease fan-out per pass — each
            # fired place spawns a handler thread agent-side — and park
            # the overflow in the retry heap, where capacity kicks and
            # lease completions pull it forward (today's steady state).
            ids = item[1]
            for aid in ids[:_SCHED_BATCH_FANOUT]:
                self._sched_actor_place(aid)
            for aid in ids[_SCHED_BATCH_FANOUT:]:
                self._sched_retry(("actor", aid), ("actor", aid))
        elif kind == "actor_lease":
            self._sched_actor_leased(*item[1:])
        elif kind == "actor_created":
            self._sched_actor_created(*item[1:])
        elif kind == "pg":
            pg_id = item[1]
            with self._lock:
                if pg_id in self._pg_running:
                    # a pass for this PG is already on the pool: coalesce
                    # (it re-enqueues itself on progress/backoff)
                    self._sched_retry(("pg", pg_id), ("pg", pg_id))
                    return
                self._pg_running.add(pg_id)

            def run(pg_id=pg_id):
                again = False
                try:
                    again = bool(self._schedule_pg_once(pg_id))
                finally:
                    with self._lock:
                        self._pg_running.discard(pg_id)
                    if again:
                        # enqueue only AFTER leaving _pg_running: enqueueing
                        # inside the pass would hit the coalesce branch and
                        # defer the (usually final) CREATED transition by a
                        # backoff cycle
                        self._sched_enqueue(("pg", pg_id))

            self._pg_pool.submit(run)
        elif kind == "kick":
            self._sched_kick()

    def _sched_actor_place(self, actor_id: str) -> None:
        """Step 1: pick a node and fire an async lease request."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] in (
                ActorState.DEAD, ActorState.ALIVE,
            ):
                return
            view = self._cluster_view_locked()
            strategy = record.get("scheduling_strategy")
            resources = record.get("resources", {})
        node_id = scheduling.pick_node(
            view, resources, strategy, self._pgs, self._lock
        )
        if node_id is None or node_id not in view:
            # not in view: a PG-bundle pick can name a node that died
            # after the snapshot — retry (the PG re-places its bundle)
            # rather than KeyError-ing the item out of the queue
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        agent_addr = view[node_id]["address"]
        try:
            pend = self._agents.get(agent_addr).call_async(
                "lease_worker",
                resources=resources,
                bundle=scheduling.pg_bundle_of(strategy),
                wait_s=0.0,
                # actor leases are store-managed: a transient store->agent
                # reconnect must not reap every actor on the node
                bind_to_conn=False,
                runtime_env=record.get("runtime_env"),
                # this node was picked from the GLOBAL view above; the
                # agent re-consulting the store for spillback would turn
                # a capacity-freed retry burst into a get_cluster_view
                # storm that parks every other RPC behind it
                spillback=False,
            )
        except RpcError as e:
            logger.warning(
                "actor %s lease on %s failed: %s", actor_id[:8], node_id[:8], e
            )
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        pend.add_done_callback(
            lambda p: self._sched_enqueue(
                ("actor_lease", actor_id, node_id, agent_addr, p)
            )
        )

    def _sched_actor_leased(self, actor_id, node_id, agent_addr, pend) -> None:
        """Step 2: lease reply arrived; fire async actor creation."""
        try:
            lease = pend.wait(0)
        except RpcError as e:
            logger.warning("actor %s lease failed: %s", actor_id[:8], e)
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        if not lease.get("granted"):
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        worker_addr = lease["worker_address"]
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                # killed while scheduling; return the lease
                try:
                    self._agents.get(agent_addr).call_oneway(
                        "release_worker", lease_id=lease["lease_id"], kill=False
                    )
                except RpcError:
                    pass
                return
            spec = dict(record)
        try:
            pend2 = self._workers.get(worker_addr).call_async(
                "create_actor", spec=spec
            )
        except RpcError as e:
            logger.warning(
                "actor %s creation on %s failed: %s", actor_id[:8], worker_addr, e
            )
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        pend2.add_done_callback(
            lambda p: self._sched_enqueue(
                ("actor_created", actor_id, node_id, agent_addr, lease, p)
            )
        )

    def _sched_actor_created(
        self, actor_id, node_id, agent_addr, lease, pend
    ) -> None:
        """Step 3: creation reply arrived; finalize ALIVE/DEAD/retry."""
        try:
            created = pend.wait(0)
        except RpcError as e:
            # transport failure: worker unusable, retry elsewhere
            logger.warning(
                "actor %s creation push failed: %s", actor_id[:8], e
            )
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            self._sched_retry(("actor", actor_id), ("actor", actor_id))
            return
        if not created.get("ok"):
            # __init__ raised: permanent, surface the error to callers
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            with self._lock:
                if actor_id in self._actors:
                    self._apply("actor_update", actor_id, {
                        "state": ActorState.DEAD,
                        "death_cause": str(created.get("error")),
                    })
            self._sched_backoff.pop(("actor", actor_id), None)
            self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
            self.publish("actor", self._public_actor(actor_id))
            return
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None:
                return
            if record["state"] == ActorState.DEAD:
                # killed while the creation push was in flight: the reply
                # must NOT resurrect it — tear the fresh worker down
                # (kill_actor found no worker_address to clean up yet)
                dead = True
            else:
                dead = False
                self._apply("actor_update", actor_id, {
                    "state": ActorState.ALIVE,
                    "node_id": node_id,
                    "worker_address": lease["worker_address"],
                    "lease_id": lease["lease_id"],
                    "agent_address": agent_addr,
                })
        if dead:
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease["lease_id"], kill=True
                )
            except RpcError:
                pass
            return
        self._sched_backoff.pop(("actor", actor_id), None)
        self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
        self.publish("actor", self._public_actor(actor_id))

    def rpc_get_actor_info(self, conn, actor_id: str):
        with self._lock:
            if actor_id not in self._actors:
                return None
            return self._public_actor(actor_id)

    def rpc_wait_actor_alive(self, conn, actor_id: str, wait_s: float = 60.0):
        # sliced server-side: clients loop (worker._resolve_actor_address)
        wait_s = min(wait_s, float(config.dispatch_wait_slice_s))
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                record = self._actors.get(actor_id)
                if record is None:
                    return None
                if record["state"] in (ActorState.ALIVE, ActorState.DEAD):
                    return self._public_actor(actor_id)
            time.sleep(0.02)
        with self._lock:
            return self._public_actor(actor_id) if actor_id in self._actors else None

    def rpc_get_named_actor(self, conn, name: str, namespace: str = "default"):
        with self._lock:
            actor_id = self._named_actors.get((namespace, name))
            if actor_id is None:
                return None
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return None
            return self._public_actor(actor_id)

    def rpc_list_actors(self, conn):
        with self._lock:
            return [self._public_actor(aid) for aid in self._actors]

    def rpc_report_actor_death(self, conn, actor_id: str, reason: str,
                               expected: bool = False):
        """Called by agents/workers when an actor's worker process exits."""
        if expected:
            self._kill_actor_internal(actor_id, reason, no_restart=True)
        else:
            self._on_actor_worker_lost(actor_id, reason)
        return True

    def rpc_report_worker_failure(self, conn, worker_address: str, node_id: str,
                                  reason: str):
        """A worker process died; fail over any actor it hosted."""
        with self._lock:
            affected = [
                a["actor_id"] for a in self._actors.values()
                if a.get("worker_address") == worker_address
                and a["state"] in (ActorState.ALIVE, ActorState.PENDING_CREATION)
            ]
        self._workers.drop(worker_address)
        for actor_id in affected:
            self._on_actor_worker_lost(actor_id, reason)
        self.publish("worker", {"event": "died", "worker_address": worker_address,
                                "node_id": node_id, "reason": reason})
        return True

    def rpc_kill_actor(self, conn, actor_id: str, no_restart: bool = True):
        self._kill_actor_internal(actor_id, "ray_tpu.kill", no_restart=no_restart)
        return True

    def rpc_kill_actors(self, conn, actor_ids: List[str],
                        no_restart: bool = True):
        """Bulk kill (ISSUE 14): one lock pass applies every DEAD
        transition (the `actor_update` mutations batch under a single
        lock acquisition), then teardown RPCs fan out across node agents
        on the bounded kill pool instead of the serial per-actor loop.
        Per-record results; unknown/already-dead ids report ok (a retried
        batch must be idempotent)."""
        results = self._kill_actors_internal(
            actor_ids, "ray_tpu.kill", no_restart=no_restart
        )
        return results

    def _kill_actors_internal(self, actor_ids: List[str], reason: str,
                              no_restart: bool) -> List[Dict[str, Any]]:
        results: List[Dict[str, Any]] = []
        doomed: List[Tuple[str, Any, Any, Any]] = []
        with self._lock:
            for actor_id in actor_ids:
                record = self._actors.get(actor_id)
                if record is None or record["state"] == ActorState.DEAD:
                    results.append(
                        {"actor_id": actor_id, "ok": True, "changed": False}
                    )
                    continue
                if no_restart:
                    self._apply("actor_update", actor_id, {
                        "state": ActorState.DEAD, "death_cause": reason,
                    })
                doomed.append((
                    actor_id,
                    record.get("worker_address"),
                    record.get("agent_address"),
                    record.get("lease_id"),
                ))
                results.append(
                    {"actor_id": actor_id, "ok": True, "changed": True}
                )
        if no_restart:
            self._sched_purge({("actor", a) for a in actor_ids})
        self._teardown_workers(doomed)
        for actor_id, _, _, _ in doomed:
            if no_restart:
                self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
                self.publish("actor", self._public_actor(actor_id))
            else:
                self._on_actor_worker_lost(actor_id, reason)
        return results

    def _teardown_workers(
        self, doomed: List[Tuple[str, Any, Any, Any]]
    ) -> None:
        """Fan worker teardown out on the bounded kill pool: one
        exit_worker oneway per worker, and the lease releases GROUPED per
        agent into one bulk release_workers RPC. The submitting thread
        never waits on an agent — in-flight is bounded by the pool size
        (config.actor_kill_fanout), and a hung agent costs one pool slot
        for the call timeout, not the whole drain."""
        by_agent: Dict[str, List[str]] = {}
        for _actor_id, worker_addr, agent_addr, lease_id in doomed:
            if worker_addr:
                self._submit_teardown(self._exit_worker_quiet, worker_addr)
            if agent_addr and lease_id:
                by_agent.setdefault(agent_addr, []).append(lease_id)
        for agent_addr, lease_ids in by_agent.items():
            self._submit_teardown(
                self._release_leases_quiet, agent_addr, lease_ids
            )

    def _submit_teardown(self, fn, *args) -> None:
        try:
            self._kill_pool.submit(fn, *args)
        except RuntimeError:  # pool shut down: store is stopping
            pass

    def _exit_worker_quiet(self, worker_addr: str) -> None:
        try:
            self._workers.get(worker_addr).call_oneway("exit_worker")
        except RpcError:
            pass
        self._workers.drop(worker_addr)

    def _release_leases_quiet(self, agent_addr: str, lease_ids: List[str]) -> None:
        try:
            self._agents.get(agent_addr).call(
                "release_workers", lease_ids=lease_ids, kill=True,
                timeout_s=10.0,
            )
        except RpcError as e:
            # agent dead/hung: its health-check death reaps the leases
            logger.warning(
                "bulk release of %d lease(s) on %s failed: %s",
                len(lease_ids), agent_addr, e,
            )

    def rpc_actor_handle_dropped(self, conn, actor_id: str):
        """The original handle went out of scope: GC the actor unless it is
        detached (parity: GcsActorManager handle-count GC)."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record.get("lifetime") == "detached":
                return False
        self._kill_actor_internal(
            actor_id, "all handles to the actor went out of scope",
            no_restart=True,
        )
        return True

    def _kill_actor_internal(self, actor_id: str, reason: str, no_restart: bool) -> None:
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return
            worker_addr = record.get("worker_address")
            agent_addr = record.get("agent_address")
            lease_id = record.get("lease_id")
            if no_restart:
                self._apply("actor_update", actor_id, {
                    "state": ActorState.DEAD, "death_cause": reason,
                })
        if no_restart:
            self._sched_purge({("actor", actor_id)})
        if worker_addr:
            try:
                self._workers.get(worker_addr).call_oneway("exit_worker")
            except RpcError:
                pass
            self._workers.drop(worker_addr)
        if agent_addr and lease_id:
            try:
                self._agents.get(agent_addr).call_oneway(
                    "release_worker", lease_id=lease_id, kill=True
                )
            except RpcError:
                pass
        if no_restart:
            self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
            self.publish("actor", self._public_actor(actor_id))
        else:
            self._on_actor_worker_lost(actor_id, reason)

    def _on_actor_worker_lost(self, actor_id: str, reason: str) -> None:
        """Restart-or-die decision (reference gcs_actor_manager.cc:1477)."""
        with self._lock:
            record = self._actors.get(actor_id)
            if record is None or record["state"] == ActorState.DEAD:
                return
            max_restarts = record.get("max_restarts", 0)
            if max_restarts == -1 or record["num_restarts"] < max_restarts:
                self._apply("actor_update", actor_id, {
                    "num_restarts": record["num_restarts"] + 1,
                    "state": ActorState.RESTARTING,
                    "worker_address": None,
                    "node_id": None,
                })
                restart = True
            else:
                self._apply("actor_update", actor_id, {
                    "state": ActorState.DEAD, "death_cause": reason,
                })
                restart = False
        if not restart:
            self._sched_purge({("actor", actor_id)})
        self.publish(f"actor:{actor_id}", self._public_actor(actor_id))
        self.publish("actor", self._public_actor(actor_id))
        if restart:
            self._sched_enqueue(("actor", actor_id))

    def _public_actor(self, actor_id: str) -> Dict[str, Any]:
        r = self._actors[actor_id]
        return {
            "actor_id": actor_id,
            "state": r["state"],
            "node_id": r.get("node_id"),
            "worker_address": r.get("worker_address"),
            "name": r.get("name"),
            "namespace": r.get("namespace", "default"),
            "class_name": r.get("class_name"),
            "method_names": r.get("method_names", []),
            "num_restarts": r.get("num_restarts", 0),
            "max_restarts": r.get("max_restarts", 0),
            "max_task_retries": r.get("max_task_retries", 0),
            "death_cause": r.get("death_cause"),
            "job_id": r.get("job_id"),
            "lifetime": r.get("lifetime"),
        }

    # ------------------------------------------------------------------
    # placement groups (reference C3: 2PC prepare/commit)
    # ------------------------------------------------------------------

    def rpc_create_placement_group(self, conn, pg_id: str, bundles: List[Dict[str, float]],
                                   strategy: str, name: Optional[str] = None,
                                   job_id: Optional[str] = None):
        with self._lock:
            self._apply("pg_add", {
                "pg_id": pg_id,
                "bundles": bundles,
                "strategy": strategy,
                "name": name,
                "job_id": job_id,
                "state": PGState.PENDING,
                # bundle index -> node_id hex
                "bundle_locations": {},
            })
        self._sched_enqueue(("pg", pg_id))
        return True

    def _schedule_pg_once(self, pg_id: str) -> bool:
        """One placement pass of a PG's missing bundles via 2PC (runs on
        the scheduler thread; infeasible/failed passes re-enqueue with
        backoff instead of parking a thread). Returns True when the caller
        should run another pass immediately (progress was made).

        Handles partial placement: only indices absent from
        bundle_locations are placed, so node-death recovery re-places the
        lost bundles while surviving bundles (and the actors in them) keep
        running — mirroring the reference GcsPlacementGroupManager's
        rescheduling of individual bundles.
        """
        key = ("pg", pg_id)
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg["state"] in (PGState.CREATED, PGState.REMOVED):
                self._sched_backoff.pop(key, None)
                return
            bundles = pg["bundles"]
            strategy = pg["strategy"]
            locations = {int(k): v for k, v in pg["bundle_locations"].items()}
            view = self._cluster_view_locked()
        missing = [i for i in range(len(bundles)) if i not in locations]
        if not missing:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None or pg["state"] == PGState.REMOVED:
                    return
                self._apply("pg_update", pg_id, {"state": PGState.CREATED})
                self._pg_cv.notify_all()
            self._sched_backoff.pop(key, None)
            self.publish(f"pg:{pg_id}", {"pg_id": pg_id, "state": PGState.CREATED})
            return
        place_view = view
        if strategy == "STRICT_SPREAD" and locations:
            survivors = set(locations.values())
            place_view = {
                nid: n for nid, n in view.items() if nid not in survivors
            }
        sub = scheduling.place_bundles(
            place_view, [bundles[i] for i in missing], strategy
        )
        if sub is None:
            self._sched_retry(("pg", pg_id), key)
            return
        placement = {missing[pos]: nid for pos, nid in sub.items()}
        # Phase 1: PREPARE on every involved agent.
        by_node: Dict[str, List[int]] = {}
        for idx, node_id in placement.items():
            by_node.setdefault(node_id, []).append(idx)
        ok = True
        for node_id, idxs in by_node.items():
            addr = view[node_id]["address"]
            try:
                res = self._agents.get(addr).call(
                    "prepare_bundles", pg_id=pg_id,
                    bundles={i: bundles[i] for i in idxs},
                )
            except RpcError:
                res = False
            if not res:
                ok = False
                break
        if not ok:
            # Roll back EVERY node in the attempted placement (by its
            # attempted indices), not just the ones that acked prepare:
            # a node whose prepare reply was lost may still hold the
            # reservation, and return_bundles on a node that never
            # prepared those indices is a no-op. Synchronous call so a
            # retried placement can't race its own rollback.
            self._rollback_bundles(view, by_node, pg_id)
            self._sched_retry(("pg", pg_id), key)
            return
        # Phase 2: COMMIT. A node that misses COMMIT would refuse
        # bundle leases forever (raylet requires state=="committed"),
        # so any commit failure rolls this placement back and retries.
        commit_ok = True
        for node_id, idxs in by_node.items():
            try:
                res = self._agents.get(view[node_id]["address"]).call(
                    "commit_bundles", pg_id=pg_id
                )
            except RpcError:
                res = False
            if not res:
                logger.warning("pg %s commit failed on %s", pg_id[:8], node_id[:8])
                commit_ok = False
        if not commit_ok:
            self._rollback_bundles(view, by_node, pg_id)
            self._sched_retry(("pg", pg_id), key)
            return
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            self._apply("pg_merge_locations", pg_id, placement)
        # go around once more: recompute missing (usually empty -> CREATED)
        return True

    def _rollback_bundles(
        self, view, by_node: Dict[str, List[int]], pg_id: str
    ) -> None:
        """Synchronously return the given bundle indices on each node (a
        one-way send could race a subsequent re-placement's prepare)."""
        for node_id, idxs in by_node.items():
            try:
                self._agents.get(view[node_id]["address"]).call(
                    "return_bundles", pg_id=pg_id, idxs=idxs
                )
            except RpcError:
                pass

    def rpc_get_placement_group(self, conn, pg_id: str):
        with self._lock:
            pg = self._pgs.get(pg_id)
            return dict(pg) if pg else None

    def rpc_wait_placement_group(self, conn, pg_id: str, wait_s: float = 60.0):
        # sliced server-side: clients loop (placement.PlacementGroup.wait)
        wait_s = min(wait_s, float(config.dispatch_wait_slice_s))
        deadline = time.monotonic() + wait_s
        with self._lock:
            while True:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    return None
                if pg["state"] in (PGState.CREATED, PGState.REMOVED):
                    return dict(pg)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return dict(pg)
                # CV, not a sleep-poll: a poll interval quantizes EVERY
                # wait that arrives before the 2PC finishes to a full
                # tick (200 PGs x 20ms was half the many-PGs bench)
                self._pg_cv.wait(remaining)

    def rpc_remove_placement_group(self, conn, pg_id: str):
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            self._apply("pg_update", pg_id, {"state": PGState.REMOVED})
            self._pg_cv.notify_all()
            locations = dict(pg["bundle_locations"])
            view = self._cluster_view_locked()
        for node_id in set(locations.values()):
            node = view.get(node_id)
            if node:
                try:
                    self._agents.get(node["address"]).call_oneway(
                        "return_bundles", pg_id=pg_id
                    )
                except RpcError:
                    pass
        self.publish(f"pg:{pg_id}", {"pg_id": pg_id, "state": PGState.REMOVED})
        return True

    def rpc_list_placement_groups(self, conn):
        with self._lock:
            return [dict(pg) for pg in self._pgs.values()]

    # ------------------------------------------------------------------

    def _cluster_view_locked(self) -> Dict[str, Dict[str, Any]]:
        return {
            nid: {
                "address": n["address"],
                "resources_total": n["resources_total"],
                "resources_available": n["resources_available"],
                "labels": n.get("labels", {}),
                "alive": True,
            }
            for nid, n in self._nodes.items()
            if n["alive"]
        }
