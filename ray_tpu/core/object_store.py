"""Shared-memory object store (plasma equivalent) + in-process memory store.

Parity targets: the plasma store (reference
src/ray/object_manager/plasma/store.h:55 — per-node shared-memory immutable
objects, clients mmap, zero-copy reads) and the CoreWorker in-process memory
store for small objects (src/ray/core_worker/store_provider/memory_store/).

TPU-first design notes: objects are single contiguous frames
(serialization.pack) so Arrow batches / numpy arrays deserialize as
zero-copy views onto the mapping — the property that lets a host feed
`jax.device_put` without an extra copy. Backing is a file in /dev/shm
(tmpfs) rather than the multiprocessing.shared_memory module, which would
fight the resource tracker across our process tree.

The store bookkeeping lives in the node agent process; workers create/seal
via agent RPC and mmap the segment directly (fd-passing equivalent of
plasma's fling.cc is unnecessary since tmpfs paths are shared on-host).
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core.exceptions import ObjectLostError
from ray_tpu.utils.ids import ObjectID

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


class ShmObjectStore:
    """Per-node store bookkeeping: create/seal/get-meta/delete segments."""

    def __init__(self, session_id: str, node_id_hex: str, capacity_bytes: int):
        self._prefix = os.path.join(
            _SHM_DIR, f"rtshm_{session_id[:8]}_{node_id_hex[:8]}"
        )
        # For validating peer-supplied paths: resolve symlinks once so the
        # comparison works even when the shm dir itself is a symlink.
        self._real_dir = os.path.realpath(_SHM_DIR)
        self._base_prefix = os.path.basename(self._prefix)
        self._capacity = capacity_bytes
        self._used = 0
        self._lock = threading.Lock()
        self._sealed_cv = threading.Condition(self._lock)
        # oid hex -> (path, size, sealed)
        self._objects: Dict[str, Tuple[str, int, bool]] = {}

    def create(self, oid_hex: str, size: int) -> str:
        # Full hex: ObjectIDs share a long job/task prefix, so any
        # truncation collides across a job's objects.
        path = f"{self._prefix}_{oid_hex}"
        with self._lock:
            if oid_hex in self._objects:
                raise ValueError(f"object {oid_hex} already exists")
            if self._used + size > self._capacity:
                raise MemoryError(
                    f"object store over capacity: used={self._used} "
                    f"request={size} cap={self._capacity}"
                )
            self._used += size
            self._objects[oid_hex] = (path, size, False)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
        finally:
            os.close(fd)
        return path

    def seal(self, oid_hex: str) -> None:
        with self._lock:
            entry = self._objects.get(oid_hex)
            if entry is None:
                raise KeyError(oid_hex)
            self._objects[oid_hex] = (entry[0], entry[1], True)
            self._sealed_cv.notify_all()

    def get_meta(
        self, oid_hex: str, timeout_s: Optional[float] = None
    ) -> Optional[Tuple[str, int]]:
        """Block until sealed (or timeout); return (path, size) or None."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while True:
                entry = self._objects.get(oid_hex)
                if entry is not None and entry[2]:
                    return entry[0], entry[1]
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._sealed_cv.wait(remaining)
                else:
                    self._sealed_cv.wait(1.0)

    def contains(self, oid_hex: str) -> bool:
        with self._lock:
            entry = self._objects.get(oid_hex)
            return entry is not None and entry[2]

    def delete(self, oid_hex: str) -> None:
        with self._lock:
            entry = self._objects.pop(oid_hex, None)
            if entry is None:
                return
            self._used -= entry[1]
        try:
            os.unlink(entry[0])
        except OSError:
            pass

    def read_chunk(self, path: str, offset: int, length: int) -> Optional[bytes]:
        """Read a byte range of a sealed segment (serving cross-node pulls).

        Only segments actually created by this store are readable: a bare
        prefix check would let a crafted '<prefix>x/../../etc/passwd' path
        escape, so resolve the path and require it to name a tracked
        object (O(1): the oid is the path suffix). A well-formed path whose
        object was deleted mid-transfer returns None — the puller maps that
        to ObjectLostError, same as a vanished segment."""
        real = os.path.realpath(path)
        base = os.path.basename(real)
        marker = self._base_prefix + "_"
        if os.path.dirname(real) != self._real_dir or not base.startswith(marker):
            raise ValueError(f"path {path} is not an object in this store")
        oid_hex = base[len(marker):]
        with self._lock:
            entry = self._objects.get(oid_hex)
        if entry is None or not entry[2]:
            return None  # deleted (or never sealed): lost, not an attack
        try:
            fd = os.open(entry[0], os.O_RDONLY)
        except OSError:
            return None
        try:
            os.lseek(fd, offset, os.SEEK_SET)
            return os.read(fd, length)
        finally:
            os.close(fd)

    def usage(self) -> Tuple[int, int]:
        with self._lock:
            return self._used, self._capacity

    def shutdown(self) -> None:
        with self._lock:
            entries = list(self._objects.values())
            self._objects.clear()
            self._used = 0
        for path, _, _ in entries:
            try:
                os.unlink(path)
            except OSError:
                pass


class ShmClient:
    """Worker-side zero-copy access to shm segments by path."""

    def __init__(self):
        self._maps: Dict[str, mmap.mmap] = {}
        self._lock = threading.Lock()

    def write(self, path: str, frame: bytes) -> None:
        fd = os.open(path, os.O_RDWR)
        try:
            with mmap.mmap(fd, len(frame)) as m:
                m[: len(frame)] = frame
        finally:
            os.close(fd)

    def read_view(self, path: str, size: int) -> memoryview:
        """mmap the segment (cached) and return a zero-copy view."""
        with self._lock:
            m = self._maps.get(path)
            if m is None:
                fd = os.open(path, os.O_RDONLY)
                try:
                    m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
                finally:
                    os.close(fd)
                self._maps[path] = m
        return memoryview(m)[:size]

    def drop(self, path: str) -> None:
        with self._lock:
            m = self._maps.pop(path, None)
        if m is not None:
            try:
                m.close()
            except (BufferError, ValueError):
                # Live numpy views still reference the mapping; leave it to GC.
                pass

    def close(self) -> None:
        with self._lock:
            maps = list(self._maps.values())
            self._maps.clear()
        for m in maps:
            try:
                m.close()
            except (BufferError, ValueError):
                pass


class MemoryStore:
    """In-process store for small objects + error markers.

    Values are stored as Python objects (already deserialized on the owner)
    or packed frames (when received from executors).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._values: Dict[ObjectID, Any] = {}

    def put(self, oid: ObjectID, value: Any) -> None:
        with self._lock:
            self._values[oid] = value
            self._cv.notify_all()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._values

    def get(self, oid: ObjectID, timeout_s: Optional[float] = None) -> Any:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while oid not in self._values:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(f"object {oid.hex()} not available")
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)
            return self._values[oid]

    def try_get(self, oid: ObjectID):
        with self._lock:
            return self._values.get(oid, _MISSING)

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._values.pop(oid, None)

    def keys(self):
        with self._lock:
            return list(self._values.keys())


class _Missing:
    __slots__ = ()

    def __repr__(self):
        return "<missing>"


_MISSING = _Missing()


def is_missing(x) -> bool:
    return x is _MISSING


class PlasmaValue:
    """Marker stored in a memory store meaning 'value lives in shm'.

    Carries the hosting node agent's address so any process can free the
    segment; same-host readers mmap the path directly, cross-host readers
    pull chunks through the hosting agent (worker.py _pull_remote_object /
    node_agent rpc_read_object_chunk)."""

    __slots__ = ("path", "size", "agent_address")

    def __init__(self, path: str, size: int, agent_address: str):
        self.path = path
        self.size = size
        self.agent_address = agent_address


class LostValue:
    """Marker meaning the value is unrecoverable (node death, eviction)."""

    __slots__ = ("message",)

    def __init__(self, message: str):
        self.message = message

    def raise_(self):
        raise ObjectLostError(self.message)
