"""Shared-memory object store (plasma equivalent) + in-process memory store.

Parity targets: the plasma store (reference
src/ray/object_manager/plasma/store.h:55 — per-node shared-memory immutable
objects, clients mmap, zero-copy reads) and the CoreWorker in-process memory
store for small objects (src/ray/core_worker/store_provider/memory_store/).

TPU-first design notes: objects are single contiguous frames
(serialization.pack) so Arrow batches / numpy arrays deserialize as
zero-copy views onto the mapping — the property that lets a host feed
`jax.device_put` without an extra copy. Backing is a file in /dev/shm
(tmpfs) rather than the multiprocessing.shared_memory module, which would
fight the resource tracker across our process tree.

The store bookkeeping lives in the node agent process; workers create/seal
via agent RPC and mmap the segment directly (fd-passing equivalent of
plasma's fling.cc is unnecessary since tmpfs paths are shared on-host).
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.exceptions import ObjectLostError
from ray_tpu.observability import core_metrics
from ray_tpu.utils import serialization
from ray_tpu.utils.ids import ObjectID

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


class _Entry:
    __slots__ = ("path", "size", "sealed", "spill_path", "last_access", "state")

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        self.sealed = False
        self.spill_path: Optional[str] = None  # set once spilled to disk
        self.last_access = time.monotonic()
        # shm | spilling | spilled | restoring — byte copies for spill and
        # restore run OUTSIDE the store lock (a GB-scale copy must not
        # stall create/seal/read for its duration); transitions are
        # finalized under the lock and announced on the store condition.
        self.state = "shm"

    @property
    def in_shm(self) -> bool:
        return self.state == "shm"


class ShmObjectStore:
    """Per-node store bookkeeping: create/seal/get-meta/delete segments,
    with LRU spill-to-disk under memory pressure.

    Spilling (parity: reference LocalObjectManager::SpillObjects,
    src/ray/raylet/local_object_manager.h:144 + plasma eviction_policy.cc):
    when a create would exceed capacity, least-recently-accessed sealed
    segments move to spill files on disk and their shm space is freed.
    Same-host readers transparently restore a spilled object into shm on
    get_meta; cross-node chunk reads are served STRAIGHT from the spill
    file (no restore — the bytes leave the node either way). Objects are
    therefore never silently lost to pressure: disk, not shm, is the
    capacity bound, and MemoryError remains only for objects larger than
    the whole store with nothing left to spill.
    """

    def __init__(self, session_id: str, node_id_hex: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self._prefix = os.path.join(
            _SHM_DIR, f"rtshm_{session_id[:8]}_{node_id_hex[:8]}"
        )
        # Segment recycle pool (plasma-arena equivalent): freeing a tmpfs
        # file returns its pages to the kernel, so every create pays page
        # allocation + zeroing again (~3x the write cost at 4 MiB).
        # Plasma dodges this by malloc'ing objects out of ONE preallocated
        # shm arena; here, deleted never-shared segments park in a rename
        # pool and the next create renames one back into place — pages
        # stay warm. Only the owner's private segments are eligible
        # (worker.delete_owned_object), so no other process can hold a
        # mapping whose bytes would change under it.
        self._recycle_prefix = os.path.join(
            _SHM_DIR, f"rtpool_{session_id[:8]}_{node_id_hex[:8]}"
        )
        self._recycle: List[Tuple[int, str]] = []  # (size, path)
        self._recycle_bytes = 0
        self._recycle_seq = 0
        self._recycle_cap = min(256 * 1024 * 1024, capacity_bytes // 4)
        # For validating peer-supplied paths: resolve symlinks once so the
        # comparison works even when the shm dir itself is a symlink.
        self._real_dir = os.path.realpath(_SHM_DIR)
        self._base_prefix = os.path.basename(self._prefix)
        self._spill_dir = spill_dir or os.path.join(
            "/tmp", f"rtspill_{session_id[:8]}_{node_id_hex[:8]}"
        )
        self._capacity = capacity_bytes
        self._used = 0
        self._spilled_bytes = 0
        # label for the per-node store gauges (cluster merge keeps the
        # latest value PER SERIES; distinct node tags keep every node)
        self._node_tag = node_id_hex[:8]
        self._lock = threading.Lock()
        self._sealed_cv = threading.Condition(self._lock)
        self._objects: Dict[str, _Entry] = {}

    def _publish_gauges_locked(self) -> None:
        """Refresh the built-in store gauges; call sites hold the lock
        (the ENABLED guard here also keeps belt-and-braces call sites
        honest)."""
        if not core_metrics.ENABLED:
            return
        tags = {"node": self._node_tag}
        core_metrics.object_store_used_bytes.set(self._used, tags=tags)
        core_metrics.object_store_spilled_bytes.set(
            self._spilled_bytes, tags=tags
        )

    # -- spill machinery -------------------------------------------------

    def _spill_victims_locked(self, need: int):
        """Oldest sealed in-shm segments totalling >= need bytes."""
        victims = []
        freed = 0
        for oid, e in sorted(
            self._objects.items(), key=lambda kv: kv[1].last_access
        ):
            if freed >= need:
                break
            if e.sealed and e.state == "shm":
                victims.append((oid, e))
                freed += e.size
        return victims if freed >= need else None

    def _copy(self, src_path: str, dst_fd: int) -> None:
        with open(src_path, "rb") as src:
            off = 0
            while True:
                buf = src.read(16 * 1024 * 1024)
                if not buf:
                    break
                os.pwrite(dst_fd, buf, off)
                off += len(buf)

    def _spill_outside_lock(self, oid_hex: str, e: _Entry) -> None:
        """Copy a segment (state already 'spilling') to disk; finalize
        under the lock. Readers may keep using the shm path until the
        unlink lands — data is immutable."""
        os.makedirs(self._spill_dir, exist_ok=True)
        spill_path = os.path.join(self._spill_dir, oid_hex)
        fd = os.open(spill_path, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o600)
        try:
            self._copy(e.path, fd)
        finally:
            os.close(fd)
        try:
            os.unlink(e.path)
        except OSError:
            pass
        with self._lock:
            e.spill_path = spill_path
            e.state = "spilled"
            self._used -= e.size
            self._spilled_bytes += e.size
            if core_metrics.ENABLED:
                core_metrics.object_store_spills.inc()
                self._publish_gauges_locked()
            self._sealed_cv.notify_all()

    def _ensure_room_locked(self, size: int) -> None:
        """Make room for `size` bytes: drain the recycle pool first (its
        pages are free the moment the file unlinks), then spill LRU
        victims. Called with the lock held; TEMPORARILY RELEASES it for
        the byte copies."""
        while True:
            # account bytes still being spilled by other threads as free-soon
            while (
                self._recycle
                and self._used + self._recycle_bytes + size > self._capacity
            ):
                rsize, rpath = self._recycle.pop()
                self._recycle_bytes -= rsize
                try:
                    os.unlink(rpath)
                except OSError:
                    pass
            if self._used + size <= self._capacity:
                return
            need = self._used + size - self._capacity
            victims = self._spill_victims_locked(need)
            if victims is None:
                if any(e.state == "spilling" for e in self._objects.values()):
                    self._sealed_cv.wait(1.0)  # someone else is freeing room
                    continue
                raise MemoryError(
                    f"object store over capacity and nothing left to spill: "
                    f"used={self._used} request={size} cap={self._capacity}"
                )
            for _, e in victims:
                e.state = "spilling"
            self._lock.release()
            try:
                for oid, e in victims:
                    self._spill_outside_lock(oid, e)
            finally:
                self._lock.acquire()

    def _restore_locked(self, oid_hex: str, e: _Entry) -> None:
        """Bring a spilled segment back into shm (for same-host mmap).
        Called with the lock held; releases it for the byte copy."""
        while True:
            while e.state in ("spilling", "restoring"):
                self._sealed_cv.wait(1.0)  # another thread is moving it
            if e.state == "shm":
                return
            self._ensure_room_locked(e.size)
            # _ensure_room_locked may have released the lock to spill
            # victims; another reader can have claimed (or completed) this
            # restore meanwhile — only one thread may claim it.
            if e.state == "spilled":
                break
        e.state = "restoring"
        self._used += e.size  # reserve before dropping the lock
        self._lock.release()
        try:
            fd = os.open(e.path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, max(e.size, 1))
                self._copy(e.spill_path, fd)
            finally:
                os.close(fd)
            try:
                os.unlink(e.spill_path)
            except OSError:
                pass
        except BaseException:
            with self._lock:
                self._used -= e.size
                e.state = "spilled"
                self._sealed_cv.notify_all()
            raise
        finally:
            self._lock.acquire()
        e.spill_path = None
        e.state = "shm"
        self._spilled_bytes -= e.size
        if core_metrics.ENABLED:
            core_metrics.object_store_restores.inc()
            self._publish_gauges_locked()
        self._sealed_cv.notify_all()

    # -- public API ------------------------------------------------------

    def create(self, oid_hex: str, size: int) -> str:
        # Full hex: ObjectIDs share a long job/task prefix, so any
        # truncation collides across a job's objects.
        path = f"{self._prefix}_{oid_hex}"
        drop_paths = []
        with self._lock:
            existing = self._objects.get(oid_hex)
            if existing is not None:
                if not existing.sealed:
                    raise ValueError(f"object {oid_hex} is being created")
                # Sealed re-create only happens when lineage reconstruction
                # re-executes a producer whose (identical, immutable) value
                # still exists after a transient failure: replace it.
                self._objects.pop(oid_hex)
                if existing.state == "shm":
                    self._used -= existing.size
                    drop_paths.append(existing.path)
                elif existing.state == "spilled":
                    self._spilled_bytes -= existing.size
                    drop_paths.append(existing.spill_path)
            # Insert first (unsealed entries are never spill victims), then
            # make room — _ensure_room_locked may release the lock while
            # spilling, and the reservation prevents duplicate creates.
            self._objects[oid_hex] = _Entry(path, size)
            self._used += size
            try:
                self._ensure_room_locked(0)
            except MemoryError:
                self._objects.pop(oid_hex, None)
                self._used -= size
                raise
            recycled = self._pop_recycle_locked(size)
            if core_metrics.ENABLED:
                self._publish_gauges_locked()
        for p in drop_paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        if recycled is not None:
            # reuse a parked segment's warm pages: rename into place and
            # trim/grow to the exact size (ftruncate frees any excess)
            try:
                os.rename(recycled[1], path)
                fd = os.open(path, os.O_RDWR)
                try:
                    os.ftruncate(fd, max(size, 1))
                finally:
                    os.close(fd)
                return path
            except OSError:
                pass  # pool file vanished: fall through to a fresh create
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, max(size, 1))
        finally:
            os.close(fd)
        return path

    def _pop_recycle_locked(self, size: int):
        """Best-fit pooled segment for a create of ``size`` bytes: the
        smallest parked file that already covers it (shrink = free pages),
        else the largest (grow = only the tail pages are cold)."""
        if not self._recycle:
            return None
        best = None
        for i, (rsize, _) in enumerate(self._recycle):
            if rsize >= size:
                if best is None or rsize < self._recycle[best][0]:
                    best = i
        if best is None:
            best = max(
                range(len(self._recycle)), key=lambda i: self._recycle[i][0]
            )
        entry = self._recycle.pop(best)
        self._recycle_bytes -= entry[0]
        return entry

    def recycle(self, oid_hex: str) -> bool:
        """Delete an object, parking its segment file in the recycle pool
        for the next create (warm pages). Only callable for never-shared
        segments — the owner guarantees no other process maps the file.
        Returns False when the entry is mid-spill/restore or not plain
        sealed shm; the caller falls back to a normal delete()."""
        with self._lock:
            entry = self._objects.get(oid_hex)
            if entry is None:
                return True
            if not entry.sealed or entry.state != "shm":
                return False
            self._objects.pop(oid_hex)
            self._used -= entry.size
            park = self._recycle_bytes + entry.size <= self._recycle_cap
            if park:
                self._recycle_seq += 1
                pool_path = f"{self._recycle_prefix}_{self._recycle_seq}"
                try:
                    os.rename(entry.path, pool_path)
                except OSError:
                    park = False
                else:
                    self._recycle.append((entry.size, pool_path))
                    self._recycle_bytes += entry.size
            if core_metrics.ENABLED:
                self._publish_gauges_locked()
        if not park:
            try:
                os.unlink(entry.path)
            except OSError:
                pass
        return True

    def seal(self, oid_hex: str) -> None:
        with self._lock:
            entry = self._objects.get(oid_hex)
            if entry is None:
                raise KeyError(oid_hex)
            entry.sealed = True
            self._sealed_cv.notify_all()

    def get_meta(
        self, oid_hex: str, timeout_s: Optional[float] = None
    ) -> Optional[Tuple[str, int]]:
        """Block until sealed (or timeout); return (path, size) or None.
        Restores a spilled segment into shm (same-host readers mmap)."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while True:
                entry = self._objects.get(oid_hex)
                if entry is not None and entry.sealed:
                    entry.last_access = time.monotonic()
                    if not entry.in_shm:
                        self._restore_locked(oid_hex, entry)
                    return entry.path, entry.size
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._sealed_cv.wait(remaining)
                else:
                    self._sealed_cv.wait(1.0)

    def contains(self, oid_hex: str) -> bool:
        with self._lock:
            entry = self._objects.get(oid_hex)
            return entry is not None and entry.sealed

    def delete(self, oid_hex: str) -> None:
        with self._lock:
            entry = self._objects.get(oid_hex)
            # a segment mid-spill/restore finishes its move first (the
            # mover assumes the entry survives until its finalize)
            while entry is not None and entry.state in ("spilling", "restoring"):
                self._sealed_cv.wait(1.0)
                entry = self._objects.get(oid_hex)
            entry = self._objects.pop(oid_hex, None)
            if entry is None:
                return
            if entry.in_shm:
                self._used -= entry.size
            else:
                self._spilled_bytes -= entry.size
            if core_metrics.ENABLED:
                self._publish_gauges_locked()
        for p in (entry.path if entry.in_shm else None, entry.spill_path):
            if p:
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _resolve_sealed(self, path: str) -> Optional[Tuple[str, int]]:
        """Resolve a segment path to (currently-backing file, size).

        Only segments actually created by this store are readable: a bare
        prefix check would let a crafted '<prefix>x/../../etc/passwd' path
        escape, so resolve the path and require it to name a tracked
        object (O(1): the oid is the path suffix). A well-formed path whose
        object was deleted mid-transfer returns None — readers map that to
        ObjectLostError, same as a vanished segment. Spilled objects serve
        straight from the spill file without restoring; an in-flight
        spill/restore is waited out (reading a path that is about to be
        unlinked would misreport a live object as lost). An UNSEALED entry
        is likewise waited out (bounded): writers seal with a oneway call,
        so a reader who learned the path from the owner's already-stored
        marker can race the seal frame across connections — the seal is
        microseconds behind, and only a dead producer leaves an entry
        unsealed for long."""
        real = os.path.realpath(path)
        base = os.path.basename(real)
        marker = self._base_prefix + "_"
        if os.path.dirname(real) != self._real_dir or not base.startswith(marker):
            raise ValueError(f"path {path} is not an object in this store")
        oid_hex = base[len(marker):]
        with self._lock:
            deadline = time.monotonic() + 10.0  # in-flight seal bound
            entry = self._objects.get(oid_hex)
            while entry is not None and (
                entry.state in ("spilling", "restoring")
                or (not entry.sealed and time.monotonic() < deadline)
            ):
                self._sealed_cv.wait(1.0)
                entry = self._objects.get(oid_hex)
            if entry is None or not entry.sealed:
                return None  # deleted (or never sealed): lost, not an attack
            entry.last_access = time.monotonic()
            return (
                entry.path if entry.in_shm else entry.spill_path,
                entry.size,
            )

    def read_chunk(self, path: str, offset: int, length: int) -> Optional[bytes]:
        """Read a byte range of a sealed segment (serving the chunked-RPC
        fallback of cross-node pulls)."""
        resolved = self._resolve_sealed(path)
        if resolved is None:
            return None
        read_path, _ = resolved
        try:
            fd = os.open(read_path, os.O_RDONLY)
        except OSError:
            return None
        try:
            os.lseek(fd, offset, os.SEEK_SET)
            parts = []
            got = 0
            while got < length:
                b = os.read(fd, length - got)
                if not b:
                    break  # EOF: short read surfaces as a partial chunk
                parts.append(b)
                got += len(b)
            return b"".join(parts)
        finally:
            os.close(fd)

    def open_for_read(self, path: str) -> Optional[Tuple[int, int]]:
        """Open the file currently backing a sealed segment for streaming
        (the data-plane server, node_agent._serve_data_conn). Returns
        (fd, size) or None when the object is gone; the open fd keeps the
        bytes alive across a concurrent spill's unlink (POSIX)."""
        resolved = self._resolve_sealed(path)
        if resolved is None:
            return None
        read_path, size = resolved
        try:
            return os.open(read_path, os.O_RDONLY), size
        except OSError:
            return None

    def usage(self) -> Tuple[int, int]:
        with self._lock:
            return self._used, self._capacity

    def inventory(self) -> List[Dict[str, Any]]:
        """Per-object listing for the state API (`state.objects()` /
        `rt memory`)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "object_id": oid,
                    "size": e.size,
                    "sealed": e.sealed,
                    "state": e.state,
                    "idle_s": round(now - e.last_access, 3),
                }
                for oid, e in self._objects.items()
            ]

    def spill_stats(self) -> Dict[str, int]:
        with self._lock:
            spilled = [e for e in self._objects.values() if not e.in_shm]
            return {
                "spilled_objects": len(spilled),
                "spilled_bytes": self._spilled_bytes,
            }

    def shutdown(self) -> None:
        with self._lock:
            entries = list(self._objects.values())
            self._objects.clear()
            self._used = 0
            self._spilled_bytes = 0
            pool = self._recycle
            self._recycle = []
            self._recycle_bytes = 0
        for e in entries:
            for p in (e.path, e.spill_path):
                if p:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
        for _, p in pool:
            try:
                os.unlink(p)
            except OSError:
                pass


def _pwrite_all(fd: int, data, offset: int) -> None:
    """pwrite until every byte lands: a single pwrite(2) caps at
    ~2 GiB (0x7ffff000) on Linux and may write short — an unchecked
    return silently truncates multi-GiB frames."""
    view = memoryview(data).cast("B")
    while len(view):
        n = os.pwrite(fd, view, offset)
        view = view[n:]
        offset += n


_IOV_CAP = 512  # stay under IOV_MAX (1024)


def pwritev_all(fd: int, parts, offset: int = 0) -> None:
    """Vectored pwrite of every buffer, resuming across short writes and
    the per-call IOV/2 GiB caps. The write-through put path: header, meta
    and pickle-5 buffers land in the segment with ONE kernel copy, no
    userspace concatenation (vs pack() + pwrite = two full copies)."""
    if not hasattr(os, "pwritev"):  # pragma: no cover — macOS/Windows
        for p in parts:
            v = memoryview(p).cast("B")
            _pwrite_all(fd, v, offset)
            offset += v.nbytes
        return
    views = serialization.byte_views(parts)
    i = 0
    while i < len(views):
        n = os.pwritev(fd, views[i:i + _IOV_CAP], offset)
        if n <= 0:
            raise OSError("pwritev made no progress")
        offset += n
        i = serialization.advance_views(views, i, n)


class ShmClient:
    """Worker-side zero-copy access to shm segments by path."""

    def __init__(self):
        self._maps: Dict[str, mmap.mmap] = {}
        self._lock = threading.Lock()

    def write(self, path: str, frame: bytes) -> None:
        # pwrite, not mmap: writing fresh tmpfs pages through a mapping
        # pays a page-fault per 4K page (~3x slower than the kernel's
        # bulk allocate+copy in write(2))
        fd = os.open(path, os.O_RDWR)
        try:
            _pwrite_all(fd, frame, 0)
        finally:
            os.close(fd)

    def read_view(self, path: str, size: int) -> memoryview:
        """mmap the segment (cached) and return a zero-copy view."""
        with self._lock:
            m = self._maps.get(path)
            if m is None:
                fd = os.open(path, os.O_RDONLY)
                try:
                    m = mmap.mmap(fd, size, prot=mmap.PROT_READ)
                finally:
                    os.close(fd)
                self._maps[path] = m
        return memoryview(m)[:size]

    def drop(self, path: str) -> None:
        with self._lock:
            m = self._maps.pop(path, None)
        if m is not None:
            try:
                m.close()
            except (BufferError, ValueError):
                # Live numpy views still reference the mapping; leave it to GC.
                pass

    def try_drop(self, path: str) -> bool:
        """Close the cached mapping for ``path`` IF nothing references it.
        True when the mapping is gone (closed now, or never existed);
        False when live views (e.g. numpy arrays a get() returned) still
        pin it — the caller must then treat the segment as shared and not
        recycle its pages."""
        with self._lock:
            m = self._maps.get(path)
            if m is None:
                return True
            try:
                m.close()
            except (BufferError, ValueError):
                return False
            del self._maps[path]
            return True

    def close(self) -> None:
        with self._lock:
            maps = list(self._maps.values())
            self._maps.clear()
        for m in maps:
            try:
                m.close()
            except (BufferError, ValueError):
                pass


class MemoryStore:
    """In-process store for small objects + error markers.

    Values are stored as Python objects (already deserialized on the owner)
    or packed frames (when received from executors).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._values: Dict[ObjectID, Any] = {}
        # external wakeups: events set on every arrival (worker.wait's
        # event-driven path registers here instead of polling)
        self._watchers: set = set()

    def put(self, oid: ObjectID, value: Any) -> None:
        with self._lock:
            self._values[oid] = value
            self._cv.notify_all()
            watchers = list(self._watchers)
        for evt in watchers:
            evt.set()

    def add_watcher(self, evt) -> None:
        with self._lock:
            self._watchers.add(evt)

    def remove_watcher(self, evt) -> None:
        with self._lock:
            self._watchers.discard(evt)

    def count_present(self, oids) -> int:
        with self._lock:
            return sum(1 for o in oids if o in self._values)

    def wait_newly_present(
        self, oids, known_present: int, timeout_s: Optional[float]
    ):
        """Block until MORE of ``oids`` are present than ``known_present``
        (or timeout); return the present subset. The event-driven core of
        wait(): arrivals notify the condition, no polling."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while True:
                present = [o for o in oids if o in self._values]
                if len(present) > known_present:
                    return present
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return present
                    self._cv.wait(min(remaining, 1.0))
                else:
                    self._cv.wait(1.0)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._values

    def get(self, oid: ObjectID, timeout_s: Optional[float] = None) -> Any:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._lock:
            while oid not in self._values:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(f"object {oid.hex()} not available")
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)
            return self._values[oid]

    def try_get(self, oid: ObjectID):
        with self._lock:
            return self._values.get(oid, _MISSING)

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._values.pop(oid, None)

    def keys(self):
        with self._lock:
            return list(self._values.keys())


class _Missing:
    __slots__ = ()

    def __repr__(self):
        return "<missing>"


_MISSING = _Missing()


def is_missing(x) -> bool:
    return x is _MISSING


class PlasmaValue:
    """Marker stored in a memory store meaning 'value lives in shm'.

    Carries the hosting node agent's address so any process can free the
    segment; same-host readers mmap the path directly, cross-host readers
    pull chunks through the hosting agent (worker.py _pull_remote_object /
    node_agent rpc_read_object_chunk).

    ``private`` is True only for segments this owner created locally
    (write-through put) whose path was never handed to another process;
    the first get_object reply that exposes the path clears it. Private
    segments are eligible for page recycling on delete
    (ShmObjectStore.recycle) — shared ones never are, because a reader's
    mapping must keep its bytes forever."""

    __slots__ = ("path", "size", "agent_address", "private")

    def __init__(self, path: str, size: int, agent_address: str,
                 private: bool = False):
        self.path = path
        self.size = size
        self.agent_address = agent_address
        self.private = private


class LostValue:
    """Marker meaning the value is unrecoverable (node death, eviction)."""

    __slots__ = ("message",)

    def __init__(self, message: str):
        self.message = message

    def raise_(self):
        raise ObjectLostError(self.message)
