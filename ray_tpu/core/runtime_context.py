"""Runtime context (parity: ray.get_runtime_context(),
python/ray/runtime_context.py)."""

from __future__ import annotations

from typing import Any, Dict, Optional


class RuntimeContext:
    def __init__(self, worker):
        self._worker = worker

    def get_job_id(self) -> str:
        return self._worker.current_job_id().hex()

    def get_task_id(self) -> Optional[str]:
        tid = self._worker.current_task_id()
        return tid.hex() if tid else None

    def get_actor_id(self) -> Optional[str]:
        return self._worker.current_actor_id()

    def get_node_id(self) -> str:
        return self._worker.node_id_hex

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    @property
    def was_current_actor_restarted(self) -> bool:
        return False  # filled by actor runtime in a later round

    def get_assigned_resources(self) -> Dict[str, float]:
        return {}

    def get(self) -> Dict[str, Any]:
        return {
            "job_id": self.get_job_id(),
            "task_id": self.get_task_id(),
            "actor_id": self.get_actor_id(),
            "node_id": self.get_node_id(),
            "worker_id": self.get_worker_id(),
        }
