"""Standalone node agent entrypoint — a non-head node joining a cluster.

Parity: the raylet binary (src/ray/raylet/main.cc). Used by
cluster_utils.Cluster to build multi-node topologies on one machine
(reference linchpin: python/ray/cluster_utils.py:135) and by `rt start`
for real multi-host deployments.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--control-address", required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument("--resources", default="{}", help="JSON resource overrides")
    parser.add_argument("--labels", default="{}", help="JSON node labels")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format=f"[node_agent {os.getpid()}] %(levelname)s %(name)s: %(message)s",
    )

    from ray_tpu.utils.config import config

    snapshot = os.environ.get("RT_CONFIG_SNAPSHOT")  # rtlint: ignore[config-hygiene] boot protocol: the snapshot must be read raw BEFORE config is populated from it
    if snapshot:
        config.load_snapshot(snapshot)

    # Crash flight recorder before anything else can segfault; the
    # agent re-points the crash dir at its session dir in start().
    from ray_tpu.observability import forensics

    forensics.install("node")

    from ray_tpu.core.node_agent import NodeAgent

    agent = NodeAgent(
        args.control_address,
        args.session_id,
        resources=json.loads(args.resources) or None,
        labels=json.loads(args.labels) or None,
    )
    agent.standalone = True
    agent.start()
    print(json.dumps({"node_id": agent.node_id.hex(), "address": agent.address}),
          flush=True)

    stop = {"flag": False}

    def handle(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, handle)
    signal.signal(signal.SIGINT, handle)
    while not stop["flag"]:
        time.sleep(0.2)
    agent.stop()


if __name__ == "__main__":
    main()
