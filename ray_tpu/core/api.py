"""Public core API.

Parity: python/ray/_private/worker.py — ray.init :1388, ray.get :2831,
ray.put :2982, ray.wait :3053, ray.kill :3233, ray.cancel :3277,
ray.get_actor :3198, @ray.remote :3453.
"""

from __future__ import annotations

import inspect
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core import worker as worker_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, make_handle_from_info, method  # noqa: F401
from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.runtime_context import RuntimeContext
from ray_tpu.core.task import RemoteFunction, TaskOptions, _merge_options
from ray_tpu.utils.config import config

_init_lock = threading.Lock()
_head_services: Optional[Dict[str, Any]] = None


class NodeAffinitySchedulingStrategy:
    """Parity: ray.util.scheduling_strategies.NodeAffinitySchedulingStrategy :43."""

    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


def is_initialized() -> bool:
    return worker_mod.global_worker_or_none() is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    object_store_memory_mb: Optional[int] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
) -> RuntimeContext:
    """Start (or connect to) a cluster and attach this process as driver.

    address=None starts a head in-process: control store + node agent run as
    threads here (reference: ray.init starting gcs_server + raylet,
    SURVEY.md §3.1); worker processes are spawned on demand.
    address="host:port" connects to an existing control store.
    """
    global _head_services
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return get_runtime_context()
            raise RuntimeError("ray_tpu.init() called twice; call shutdown() first")

        if object_store_memory_mb is not None:
            config.set("object_store_memory_mb", object_store_memory_mb)

        remote_driver = False
        if address is not None and address.startswith("rt://"):
            # remote-driver mode (reference ray:// client): everything
            # rides the head gateway — the only address we can reach
            from ray_tpu.utils import gateway as gateway_mod

            gw_addr = address[len("rt://"):]
            info = gateway_mod.fetch_info(gw_addr)
            gateway_mod.set_gateway(gw_addr)
            address = info["control_address"]
            remote_driver = True
        if address is None:
            from ray_tpu.core.control_store import ControlStore
            from ray_tpu.core.node_agent import NodeAgent
            from ray_tpu.utils.gateway import Gateway

            session_id = uuid.uuid4().hex
            control = ControlStore(session_id)
            control.start()
            gateway_srv = Gateway(control.address)
            gateway_srv.start()
            res_override: Dict[str, float] = dict(resources or {})
            if num_cpus is not None:
                res_override["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res_override["TPU"] = float(num_tpus)
            agent = NodeAgent(
                control.address, session_id,
                resources=res_override or None, labels=labels,
            )
            agent.start()
            _head_services = {
                "control": control, "agent": agent, "gateway": gateway_srv,
            }
            control_address = control.address
            agent_address = agent.address
            node_id_hex = agent.node_id.hex()
        else:
            control_address = address
            # pick an agent on this cluster to act as our local object/lease
            # endpoint (the driver host's own agent in a real deployment)
            from ray_tpu.utils.rpc import RpcClient

            probe = RpcClient(control_address, name="probe")
            nodes = probe.call("get_nodes", retryable=True)
            probe.close()
            if not nodes:
                raise RayTpuError(f"no alive nodes at {address}")
            agent_address = nodes[0]["address"]
            node_id_hex = nodes[0]["node_id"]
            session_id = "joined"

        w = worker_mod.CoreWorker(
            mode="driver",
            control_address=control_address,
            node_agent_address=agent_address,
            session_id=session_id,
            node_id_hex=node_id_hex,
        )
        w.namespace = namespace
        if remote_driver:
            w.enable_gateway_mode()
        w.connect_driver()
        worker_mod.set_global_worker(w)
        from ray_tpu import usage

        usage.record("init", mode="head" if address is None else "client")
        return RuntimeContext(w)


def shutdown() -> None:
    global _head_services
    with _init_lock:
        w = worker_mod.global_worker_or_none()
        if w is not None:
            try:
                w.control.call("finish_job", job_id=w.job_id.hex(), timeout_s=10.0)
            except Exception:  # noqa: BLE001 — control store may be gone
                pass
            w.shutdown()
            worker_mod.set_global_worker(None)
        if _head_services is not None:
            _head_services["agent"].stop()
            _head_services["control"].stop()
            gw = _head_services.get("gateway")
            if gw is not None:
                gw.stop()
            _head_services = None
        from ray_tpu.utils import gateway as gateway_mod

        gateway_mod.set_gateway(None)


def remote(*args, **options):
    """@remote decorator for functions and classes (parity: worker.py:3453)."""

    def decorate(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        opts = _merge_options(TaskOptions(), **options)
        return RemoteFunction(obj, opts)

    if len(args) == 1 and not options and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorate


def put(value: Any, *, _tensor_transport: str = "object") -> ObjectRef:
    """Store a value and return a ref. ``_tensor_transport="device"``
    keeps jax.Array leaves device-resident (TPU-RDT; parity:
    ray.put(_tensor_transport=...), reference gpu_object_manager)."""
    from ray_tpu.core.device_objects import validate_transport

    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return worker_mod.global_worker().put(
        value, tensor_transport=validate_transport(_tensor_transport)
    )


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
):
    w = worker_mod.global_worker()
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
    values = w.get(ref_list, timeout_s=timeout)
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in [1, {len(refs)}]")
    return worker_mod.global_worker().wait(
        refs, num_returns=num_returns, timeout_s=timeout, fetch_local=fetch_local
    )


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    worker_mod.global_worker().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    """Cancel a task (reference core_worker.h Cancel): queued tasks are
    dropped, a running task gets KeyboardInterrupt, force=True kills its
    worker. ``recursive`` is accepted for signature parity but child
    tasks spawned by the cancelled task are NOT chased — ownership of
    children lives with the executing worker, which force-kill tears
    down anyway; a cooperative child-cancellation protocol is future
    work."""
    worker_mod.global_worker().cancel_task(ref, force=force)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = worker_mod.global_worker()
    info = w.control.call("get_named_actor", name=name, namespace=namespace)
    if info is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    return make_handle_from_info(info)


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(worker_mod.global_worker())


def nodes() -> List[Dict[str, Any]]:
    return worker_mod.global_worker().control.call("get_nodes")


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    w = worker_mod.global_worker()
    view = w.control.call("get_cluster_view")
    total: Dict[str, float] = {}
    for n in view.values():
        for k, v in n["resources_available"].items():
            total[k] = total.get(k, 0.0) + v
    return total
