"""Actor API: ActorClass / ActorHandle / ActorMethod.

Parity: python/ray/actor.py — ActorClass._remote :793, ActorHandle :1878.
Handles are plain pickleable records (actor_id + method metadata); the
receiving process routes calls through its own CoreWorker, resolving the
actor's current address from the control store (reference: caller resolves
actor location via GCS subscribe, SURVEY.md §3.3).
"""

from __future__ import annotations

import hashlib
import inspect
from typing import Any, Dict, List, Optional

from ray_tpu.utils import serialization

_ACTOR_OPTION_KEYS = {
    "name", "namespace", "lifetime", "max_restarts", "max_concurrency",
    "num_cpus", "num_tpus", "num_gpus", "resources", "scheduling_strategy",
    "max_task_retries", "runtime_env", "concurrency_groups",
}


def method(num_returns=1, tensor_transport: str = "object",
           concurrency_group: Optional[str] = None):
    """Decorator configuring an actor method (parity: ray.method —
    including the RDT ``tensor_transport`` option, reference
    gpu_object_manager.py; ``num_returns="streaming"`` for generator
    methods that yield through an ObjectRefGenerator; and
    ``concurrency_group`` routing the method onto a named per-group
    thread pool, reference concurrency_group_manager.h:38)."""

    from ray_tpu.core.device_objects import validate_transport

    validate_transport(tensor_transport)

    def wrap(fn):
        fn.__rt_num_returns__ = num_returns
        fn.__rt_tensor_transport__ = tensor_transport
        if concurrency_group is not None:
            fn.__rt_concurrency_group__ = concurrency_group
        return fn

    return wrap


class ActorClass:
    def __init__(self, cls, options: Dict[str, Any]):
        self._cls = cls
        self._options = dict(options)
        self._blob: Optional[bytes] = None
        self._class_id: Optional[str] = None
        self.__name__ = cls.__name__

    @property
    def cls(self):
        return self._cls

    def options(self, **kwargs) -> "ActorClass":
        unknown = set(kwargs) - _ACTOR_OPTION_KEYS
        if unknown:
            raise TypeError(f"unknown actor options: {sorted(unknown)}")
        merged = {**self._options, **kwargs}
        clone = ActorClass(self._cls, merged)
        clone._blob, clone._class_id = self._blob, self._class_id
        return clone

    def _class_blob(self):
        if self._blob is None:
            blob = serialization.dumps_function(self._cls)
            self._blob = blob
            self._class_id = "cls_" + hashlib.sha1(blob).hexdigest()[:24]
        return self._class_id, self._blob

    def _method_meta(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {}
        for name, fn in inspect.getmembers(self._cls, callable):
            if name.startswith("__") and name != "__call__":
                continue
            nr = getattr(fn, "__rt_num_returns__", 1)
            tt = getattr(fn, "__rt_tensor_transport__", "object")
            meta[name] = nr if tt == "object" else (nr, tt)
        return meta

    def remote(self, *args, **kwargs) -> "ActorHandle":
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        class_id, blob = self._class_blob()
        opts = dict(self._options)
        resources = dict(opts.get("resources") or {})
        num_cpus = opts.get("num_cpus")
        if num_cpus is None:
            num_cpus = 1.0 if not resources and not opts.get("num_tpus") else 0.0
        if num_cpus:
            resources["CPU"] = float(num_cpus)
        num_tpus = opts.get("num_tpus") or opts.get("num_gpus")
        if num_tpus:
            resources["TPU"] = float(num_tpus)
        opts["resources"] = resources
        method_meta = self._method_meta()
        opts["method_names"] = sorted(method_meta)
        groups = opts.get("concurrency_groups")
        method_groups = {
            name: getattr(fn, "__rt_concurrency_group__")
            for name, fn in inspect.getmembers(self._cls, callable)
            if getattr(fn, "__rt_concurrency_group__", None) is not None
        }
        if method_groups and not groups:
            raise ValueError(
                "methods declare concurrency_group "
                f"{sorted(set(method_groups.values()))} but the actor has "
                "no concurrency_groups option"
            )
        unknown = set(method_groups.values()) - set(groups or {})
        if unknown:
            raise ValueError(
                f"methods reference undeclared concurrency groups "
                f"{sorted(unknown)}"
            )
        opts["method_groups"] = method_groups
        actor_id = w.create_actor(
            class_id, blob, self.__name__, args, kwargs, opts
        )
        return ActorHandle(actor_id, self.__name__, method_meta, owner=True)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()."
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 tensor_transport: str = "object"):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._tensor_transport = tensor_transport

    def options(self, num_returns: Optional[int] = None,
                tensor_transport: Optional[str] = None) -> "ActorMethod":
        if tensor_transport is not None:
            from ray_tpu.core.device_objects import validate_transport

            validate_transport(tensor_transport)
        return ActorMethod(
            self._handle, self._name,
            num_returns if num_returns is not None else self._num_returns,
            tensor_transport if tensor_transport is not None
            else self._tensor_transport,
        )

    def remote(self, *args, **kwargs):
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        nr = -1 if self._num_returns == "streaming" else self._num_returns
        refs = w.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=nr,
            tensor_transport=self._tensor_transport,
        )
        if nr in (1, -1):  # single ref, or the ObjectRefGenerator
            return refs[0]
        return refs

    def bind(self, *args):
        """Create a static-DAG node for this method (compiled graphs,
        ray_tpu/dag.py; parity: python/ray/dag/dag_node.py bind)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._name} cannot be called directly; "
            f"use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str, method_meta: Dict[str, int],
                 owner: bool = False):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta
        # The handle returned by ActorClass.remote() is the "original handle";
        # when it goes out of scope the (non-detached) actor is killed —
        # parity with the reference's actor GC, where the GCS kills an actor
        # once its creator's handle count drops to zero.
        self._owner = owner

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_meta and name not in self._method_meta:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}"
            )
        meta = self._method_meta.get(name, 1)
        if isinstance(meta, tuple):
            num_returns, transport = meta
        else:
            num_returns, transport = meta, "object"
        return ActorMethod(self, name, num_returns, transport)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:8]})"

    def __reduce__(self):
        # pickled copies are borrowers, never owners
        return (ActorHandle, (self._actor_id, self._class_name, self._method_meta))

    def __del__(self):
        if not getattr(self, "_owner", False):
            return
        try:
            from ray_tpu.core import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is not None and not w._shutdown.is_set():
                # via the worker so the drop orders after a still-batched
                # registration of this very actor (core/worker.py
                # drop_actor_handle)
                w.drop_actor_handle(self._actor_id)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def make_handle_from_info(info: Dict[str, Any]) -> ActorHandle:
    """Build a handle from a control-store actor record (get_actor path)."""
    method_names: List[str] = info.get("method_names") or []
    return ActorHandle(
        info["actor_id"], info.get("class_name", "Actor"),
        {m: 1 for m in method_names},
    )
