"""User-visible exceptions (parity: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get().

    Parity: ray.exceptions.RayTaskError — carries the remote traceback.
    """

    def __init__(self, message: str, remote_traceback: str = "", cause=None):
        super().__init__(message)
        self.remote_traceback = remote_traceback
        self.cause = cause

    def __str__(self):
        base = super().__str__()
        if self.remote_traceback:
            return f"{base}\n\n--- remote traceback ---\n{self.remote_traceback}"
        return base


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; no more method calls will succeed."""

    def __init__(self, message: str = "The actor died.", actor_id=None):
        super().__init__(message)
        self.actor_id = actor_id


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object value was lost and could not be reconstructed."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class TaskCancelledError(RayTpuError):
    """Task was cancelled before or during execution."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class NodeDiedError(RayTpuError):
    """A node was lost while work depended on it."""


class PlacementGroupError(RayTpuError):
    """Placement group creation/usage error."""


class CollectiveError(RayTpuError):
    """A host collective failed: a peer died, a ring transfer could not
    be delivered, or the op deadline passed. Raised on every surviving
    rank (the detecting rank poisons the ring so peers fail fast instead
    of hanging)."""
