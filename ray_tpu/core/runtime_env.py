"""Runtime environments: per-task/actor env_vars and working_dir.

Parity: the reference runtime-env plugin system (C17/P9 —
python/ray/_private/runtime_env/{working_dir,...}.py + the per-node
agent's URI cache). Scope here is the two plugins everything else builds
on: env_vars (set for the duration of the execution) and working_dir
(the driver zips the directory into the control-store KV once,
content-addressed; executors download/extract/cache it and run with it
as cwd + on sys.path). pip/conda envs are out of scope in this
no-network image — the by-value cloudpickle of user modules
(utils/serialization.py) covers driver-local code instead.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import threading
import zipfile
from typing import Any, Dict, Optional

_KV_NS = "runtime_env"
_MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024
_cache_lock = threading.Lock()
_extracted: Dict[str, str] = {}  # digest -> extracted path
_uploaded: Dict[str, str] = {}  # abs working_dir path -> digest


def _content_digest(blob: bytes) -> str:
    """Content address for working_dir packages: native xxHash64
    (native/src/store_core.cpp — same role as the reference's package
    hashing in runtime_env packaging) with a sha1 fallback. The two never
    mix within one cluster: keys are generated on the driver and looked
    up verbatim."""
    from ray_tpu import native

    lib = native.store_lib()
    if lib is not None:
        return f"xxh64-{lib.rt_xxh64(blob, len(blob), 0):016x}"
    return hashlib.sha1(blob).hexdigest()


def prepare(runtime_env: Optional[Dict[str, Any]], control) -> Optional[Dict[str, Any]]:
    """Driver-side: normalize + upload. working_dir paths become
    content-addressed KV references, uploaded ONCE per directory path per
    process (the reference packages a working_dir URI once per job —
    re-zipping 100MB on every .remote() would turn submission into pure
    CPU; edit-and-resubmit within one driver process reuses the first
    upload)."""
    if not runtime_env:
        return None
    out = dict(runtime_env)
    wd = out.get("working_dir")
    if wd and not isinstance(wd, dict):
        wd = os.path.abspath(wd)
        with _cache_lock:
            digest = _uploaded.get(wd)
        if digest is not None:
            out["working_dir"] = {"kv_key": digest}
            if out.get("env_vars") is not None:
                out["env_vars"] = {
                    str(k): str(v) for k, v in out["env_vars"].items()
                }
            return out
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        buf = io.BytesIO()
        total = 0
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, dirs, files in os.walk(wd):
                dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
                for name in files:
                    path = os.path.join(root, name)
                    total += os.path.getsize(path)
                    if total > _MAX_WORKING_DIR_BYTES:
                        raise ValueError(
                            f"working_dir {wd!r} exceeds "
                            f"{_MAX_WORKING_DIR_BYTES >> 20}MB"
                        )
                    zf.write(path, os.path.relpath(path, wd))
        blob = buf.getvalue()
        digest = _content_digest(blob)
        control.call(
            "kv_put", ns=_KV_NS, key=digest, value=blob, overwrite=False,
            retryable=True,
        )
        with _cache_lock:
            _uploaded[wd] = digest
        out["working_dir"] = {"kv_key": digest}
    env_vars = out.get("env_vars")
    if env_vars is not None:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    return out


def _fetch_working_dir(digest: str, control) -> str:
    with _cache_lock:
        path = _extracted.get(digest)
    if path and os.path.isdir(path):
        return path
    blob = control.call("kv_get", ns=_KV_NS, key=digest, retryable=True)
    if blob is None:
        raise RuntimeError(f"working_dir blob {digest} missing from KV")
    target = os.path.join("/tmp", f"rtenv_{digest[:16]}")
    tmp = target + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # another worker won the race; use theirs
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    with _cache_lock:
        _extracted[digest] = target
    return target


def apply_permanent(runtime_env: Optional[Dict[str, Any]], control) -> None:
    """Executor-side, for actors: the worker process is dedicated to one
    actor, so its runtime env applies for the process's whole life (no
    restore). Same semantics as one `apply` entered forever."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if isinstance(wd, dict) and "kv_key" in wd:
        path = _fetch_working_dir(wd["kv_key"], control)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)


@contextlib.contextmanager
def apply(runtime_env: Optional[Dict[str, Any]], control):
    """Executor-side: env vars + working_dir for one execution.

    Env vars are process-wide (worker processes execute at most one
    runtime-env-bearing task at a time in practice; the reference
    instead keys whole worker processes by env hash — worker-pool
    binning is a follow-up)."""
    if not runtime_env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = None
    added_path = None
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = runtime_env.get("working_dir")
        if isinstance(wd, dict) and "kv_key" in wd:
            path = _fetch_working_dir(wd["kv_key"], control)
            saved_cwd = os.getcwd()
            os.chdir(path)
            if path not in sys.path:
                sys.path.insert(0, path)
                added_path = path
        yield
    finally:
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        if added_path is not None:
            try:
                sys.path.remove(added_path)
            except ValueError:
                pass
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
