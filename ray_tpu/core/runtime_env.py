"""Runtime environments: env_vars, working_dir, py_modules, pip.

Parity: the reference runtime-env plugin system (C17/P9 —
python/ray/_private/runtime_env/{working_dir,py_modules,pip,uv}.py + the
per-node agent's URI cache). Plugins here:

- env_vars: set for the duration of the execution (or the worker's life
  for actors / env-booted workers);
- working_dir: the driver zips the directory into the control-store KV
  once (content-addressed); executors extract/cache and run with it as
  cwd + on sys.path;
- py_modules: like working_dir but each entry is one module/package
  directory placed on sys.path (no chdir) — several jobs can ship
  DIFFERENT versions of the same module name and stay isolated because
  the worker pool is keyed by runtime-env hash;
- pip: a venv (--system-site-packages, so ray_tpu and jax resolve from
  the base image) with the requested packages installed OFFLINE from
  the local wheel directories in ``config.pip_find_links`` (this image
  has no egress — the reference's pip/uv plugin hits PyPI instead).
  Workers for a pip env are spawned from the env's own interpreter.

The node agent keys its worker pool by ``env_hash`` (reference
worker_pool.h:280): repeated use of one env lands on warm, already-
booted workers, and executions whose env matches the worker's boot env
skip per-task apply entirely.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import threading
import zipfile
from typing import Any, Dict, Optional

_KV_NS = "runtime_env"
_MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024
_cache_lock = threading.Lock()
_extracted: Dict[str, str] = {}  # digest -> extracted path
_uploaded: Dict[str, str] = {}  # abs working_dir path -> digest


def _content_digest(blob: bytes) -> str:
    """Content address for working_dir packages: native xxHash64
    (native/src/store_core.cpp — same role as the reference's package
    hashing in runtime_env packaging) with a sha1 fallback. The two never
    mix within one cluster: keys are generated on the driver and looked
    up verbatim."""
    from ray_tpu import native

    lib = native.store_lib()
    if lib is not None:
        return f"xxh64-{lib.rt_xxh64(blob, len(blob), 0):016x}"
    return hashlib.sha1(blob).hexdigest()


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable identity of a PREPARED runtime env — the worker-pool key
    (reference: runtime_env_hash on the lease spec, worker_pool.h:280).
    Empty env hashes to "" (the default pool)."""
    if not runtime_env:
        return ""
    import json

    blob = json.dumps(runtime_env, sort_keys=True, default=str).encode()
    return _content_digest(blob)


def _zip_dir(path: str, arc_prefix: str = "") -> bytes:
    """Deterministic zip of a directory (no timestamps — the digest must
    be stable across re-zips of identical content)."""
    buf = io.BytesIO()
    total = 0
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(
            d for d in dirs if d not in ("__pycache__", ".git")
        )
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, path)
            if arc_prefix:
                rel = os.path.join(arc_prefix, rel)
            entries.append((full, rel))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for full, rel in entries:
            total += os.path.getsize(full)
            if total > _MAX_WORKING_DIR_BYTES:
                raise ValueError(
                    f"{path!r} exceeds {_MAX_WORKING_DIR_BYTES >> 20}MB"
                )
            info = zipfile.ZipInfo(rel)  # fixed (1980) timestamp
            # a bare ZipInfo defaults to STORED and zero permissions:
            # keep deflate and the file mode (an executable script must
            # stay +x after extraction)
            info.compress_type = zipfile.ZIP_DEFLATED
            info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as f:
                zf.writestr(info, f.read())
    return buf.getvalue()


def _upload_blob(blob: bytes, control) -> str:
    digest = _content_digest(blob)
    control.call(
        "kv_put", ns=_KV_NS, key=digest, value=blob, overwrite=False,
        retryable=True,
    )
    return digest


def prepare(runtime_env: Optional[Dict[str, Any]], control) -> Optional[Dict[str, Any]]:
    """Driver-side: normalize + upload. working_dir paths become
    content-addressed KV references, uploaded ONCE per directory path per
    process (the reference packages a working_dir URI once per job —
    re-zipping 100MB on every .remote() would turn submission into pure
    CPU; edit-and-resubmit within one driver process reuses the first
    upload)."""
    if not runtime_env:
        return None
    out = dict(runtime_env)
    wd = out.get("working_dir")
    if wd and not isinstance(wd, dict):
        wd = os.path.abspath(wd)
        with _cache_lock:
            digest = _uploaded.get(("wd", wd))
        if digest is not None:
            out["working_dir"] = {"kv_key": digest}
            if out.get("env_vars") is not None:
                out["env_vars"] = {
                    str(k): str(v) for k, v in out["env_vars"].items()
                }
            return out
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        digest = _upload_blob(_zip_dir(wd), control)
        with _cache_lock:
            _uploaded[("wd", wd)] = digest
        out["working_dir"] = {"kv_key": digest}
    mods = out.get("py_modules")
    if mods:
        prepared = []
        for mod in mods:
            if isinstance(mod, dict):
                prepared.append(mod)  # already uploaded
                continue
            mod = os.path.abspath(mod)
            with _cache_lock:
                # keyed by (kind, path): a py_modules zip carries the
                # package-name arc prefix a working_dir zip must not
                digest = _uploaded.get(("mod", mod))
            if digest is None:
                if not os.path.isdir(mod):
                    raise ValueError(
                        f"py_modules entry {mod!r} is not a directory"
                    )
                # zip UNDER the package name so extraction yields an
                # importable <name>/ on sys.path
                digest = _upload_blob(
                    _zip_dir(mod, arc_prefix=os.path.basename(mod)),
                    control,
                )
                with _cache_lock:
                    _uploaded[("mod", mod)] = digest
            prepared.append(
                {"kv_key": digest, "name": os.path.basename(mod)}
            )
        out["py_modules"] = prepared
    pip = out.get("pip")
    if pip:
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        out["pip"] = sorted(str(p) for p in pip)
    env_vars = out.get("env_vars")
    if env_vars is not None:
        out["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    return out


def ensure_pip_env(packages) -> str:
    """Node-side: create (or reuse) a venv with ``packages`` installed
    from the local wheel dirs, returning its python executable. Offline
    by design: ``--no-index --find-links <config.pip_find_links>`` (this
    image has no egress; the reference pip/uv plugin would hit an index).
    Content-addressed by the sorted package list; creation is
    single-flight per env across threads (a marker file makes it
    idempotent across processes on one host)."""
    import json
    import subprocess
    import sys as sys_mod

    from ray_tpu.utils.config import config

    packages = sorted(str(p) for p in packages)
    key = _content_digest(json.dumps(packages).encode())[:16]
    env_dir = os.path.join(str(config.temp_dir), "pip_envs", key)
    python = os.path.join(env_dir, "bin", "python")
    marker = os.path.join(env_dir, ".rt_ready")
    with _pip_lock:
        if os.path.exists(marker):
            return python
        tmp = env_dir + f".tmp{os.getpid()}"
        import venv

        venv.EnvBuilder(
            system_site_packages=True, with_pip=True, symlinks=True
        ).create(tmp)
        # venv-from-venv: --system-site-packages exposes the BASE
        # interpreter's site dirs, not this (already-virtual) parent's —
        # bridge the parent's site-packages with a .pth so ray_tpu's own
        # dependencies (cloudpickle, numpy, jax) stay importable
        import site

        parent_sites = [
            p for p in site.getsitepackages() + sys_mod.path
            if p.endswith("site-packages") and os.path.isdir(p)
        ]
        lib = os.path.join(tmp, "lib")
        (pydir,) = [d for d in os.listdir(lib) if d.startswith("python")]
        pth = os.path.join(lib, pydir, "site-packages", "rt_parent.pth")
        with open(pth, "w") as f:
            f.write("\n".join(dict.fromkeys(parent_sites)) + "\n")
        find_links = [
            d for d in str(config.pip_find_links).split(os.pathsep) if d
        ]
        cmd = [
            os.path.join(tmp, "bin", "python"), "-m", "pip", "install",
            "--quiet", "--no-index",
        ]
        for d in find_links:
            cmd += ["--find-links", d]
        cmd += packages
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True,
                timeout=600,
            )
        except subprocess.CalledProcessError as e:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip env creation failed for {packages}: {e.stderr[-2000:]}"
            ) from None
        try:
            os.rename(tmp, env_dir)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # lost a cross-process race
        open(marker, "w").close()
        return python


_pip_lock = threading.Lock()


def _fetch_working_dir(digest: str, control) -> str:
    with _cache_lock:
        path = _extracted.get(digest)
    if path and os.path.isdir(path):
        return path
    blob = control.call("kv_get", ns=_KV_NS, key=digest, retryable=True)
    if blob is None:
        raise RuntimeError(f"working_dir blob {digest} missing from KV")
    target = os.path.join("/tmp", f"rtenv_{digest[:16]}")
    tmp = target + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        # another worker won the race; use theirs
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    with _cache_lock:
        _extracted[digest] = target
    return target


def _pip_site_dir(packages) -> str:
    """The env's site-packages dir (creating the env if needed)."""
    python = ensure_pip_env(packages)
    env_dir = os.path.dirname(os.path.dirname(python))
    lib = os.path.join(env_dir, "lib")
    (pydir,) = [d for d in os.listdir(lib) if d.startswith("python")]
    return os.path.join(lib, pydir, "site-packages")


def _in_pip_env(packages) -> bool:
    """True when THIS interpreter already is the env's python (the
    worker was spawned from it — the env-keyed pool's normal case)."""
    import json

    key = _content_digest(
        json.dumps(sorted(str(p) for p in packages)).encode()
    )[:16]
    return os.path.basename(sys.prefix) == key


def apply_permanent(runtime_env: Optional[Dict[str, Any]], control) -> None:
    """Executor-side, for actors and env-booted workers: the process is
    dedicated to one env, so it applies for the process's whole life (no
    restore). Same semantics as one `apply` entered forever."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    for mod in runtime_env.get("py_modules") or []:
        if isinstance(mod, dict) and "kv_key" in mod:
            path = _fetch_working_dir(mod["kv_key"], control)
            if path not in sys.path:
                sys.path.insert(0, path)
    pip = runtime_env.get("pip")
    if pip and not _in_pip_env(pip):
        # fallback for a worker NOT spawned from the env python (pool
        # miss): pure-python packages resolve via the env's site dir
        import site

        site.addsitedir(_pip_site_dir(pip))
    wd = runtime_env.get("working_dir")
    if isinstance(wd, dict) and "kv_key" in wd:
        path = _fetch_working_dir(wd["kv_key"], control)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)


@contextlib.contextmanager
def apply(runtime_env: Optional[Dict[str, Any]], control):
    """Executor-side: env vars + working_dir for one execution.

    Env vars are process-wide (worker processes execute at most one
    runtime-env-bearing task at a time in practice; the reference
    instead keys whole worker processes by env hash — worker-pool
    binning is a follow-up)."""
    if not runtime_env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = None
    added_paths = []
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for mod in runtime_env.get("py_modules") or []:
            if isinstance(mod, dict) and "kv_key" in mod:
                path = _fetch_working_dir(mod["kv_key"], control)
                if path not in sys.path:
                    sys.path.insert(0, path)
                    added_paths.append(path)
        pip = runtime_env.get("pip")
        if pip and not _in_pip_env(pip):
            site_dir = _pip_site_dir(pip)
            if site_dir not in sys.path:
                sys.path.insert(0, site_dir)
                added_paths.append(site_dir)
        wd = runtime_env.get("working_dir")
        if isinstance(wd, dict) and "kv_key" in wd:
            path = _fetch_working_dir(wd["kv_key"], control)
            saved_cwd = os.getcwd()
            os.chdir(path)
            if path not in sys.path:
                sys.path.insert(0, path)
                added_paths.append(path)
        yield
    finally:
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for path in added_paths:
            try:
                sys.path.remove(path)
            except ValueError:
                pass
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
