"""Worker process entrypoint (parity: python/ray/_private/workers/
default_worker.py). Spawned by the node agent's worker pool.

Deliberately import-light: no JAX import at startup so the pool can spin up
workers in ~100ms; JAX loads lazily the first time a task touches it.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import time


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-address", required=True)
    parser.add_argument("--control-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument("--kind", default="cpu")
    parser.add_argument("--env-hash", default="")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {os.getpid()}] %(levelname)s %(name)s: %(message)s",
    )

    from ray_tpu.utils.config import config

    snapshot = os.environ.get("RT_CONFIG_SNAPSHOT")  # rtlint: ignore[config-hygiene] boot protocol: the snapshot must be read raw BEFORE config is populated from it
    if snapshot:
        config.load_snapshot(snapshot)

    # Crash flight recorder FIRST (satellite contract: independent of
    # profiler flags) — a SIGSEGV in native channel/shm code must leave
    # a traceback even if the worker dies before registering. The black
    # box thread inside install() is observability-gated.
    from ray_tpu.observability import forensics

    forensics.install("worker")

    from ray_tpu.core import worker as worker_mod

    w = worker_mod.CoreWorker(
        mode="worker",
        control_address=args.control_address,
        node_agent_address=args.node_address,
        session_id=args.session_id,
        node_id_hex=args.node_id,
    )
    w.worker_kind = args.kind
    w.boot_env_hash = args.env_hash
    boot_env = os.environ.get("RT_BOOT_ENV")  # rtlint: ignore[config-hygiene] boot protocol: set per-process by the node agent at spawn, not a cluster flag
    if boot_env:
        # env-keyed pool: this worker is dedicated to one runtime env —
        # apply it for the process's whole life BEFORE registering, so a
        # lease granted against our env_hash lands on a ready worker
        import base64

        from ray_tpu.core import runtime_env as runtime_env_mod
        from ray_tpu.utils import serialization

        spec = serialization.loads(base64.b64decode(boot_env))
        runtime_env_mod.apply_permanent(spec, w.control)
        w.boot_env_spec = spec
    worker_mod.set_global_worker(w)
    w.connect_worker()

    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
