"""Node agent — per-host daemon: scheduler, worker pool, object store host.

Parity: the raylet (reference src/ray/raylet/node_manager.h:140 —
HandleRequestWorkerLease :290), WorkerPool (worker_pool.h:280), the
placement-group resource manager (placement_group_resource_manager.h:57-64,
PREPARE/COMMIT bundle carve-outs as named pools), and the plasma store host
(the ShmObjectStore bookkeeping lives here; workers mmap segments
directly).

TPU-first: node resources include "TPU" chips and slice-topology labels
discovered by ray_tpu.accelerators (parity: the reference's accelerator
plugin python/ray/_private/accelerators/tpu.py:291 which models TPU as a
schedulable resource + "TPU-<pod_type>-head" marker).

Lease protocol (hot path, mirrors §3.2 of SURVEY.md):
  owner → lease_worker(resources, bundle?) →
    {"granted": True, worker_address, lease_id}                  (local grant)
  | {"granted": False, "spillback": "<other agent address>"}      (spill)
  owner pushes tasks directly to the worker, then release_worker(lease_id).
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import scheduling
from ray_tpu.core.object_store import ShmObjectStore
from ray_tpu.observability import core_metrics, forensics, profiler
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config
from ray_tpu.utils.ids import NodeID
from ray_tpu.utils.rpc import RpcClient, RpcError, RpcServer

logger = logging.getLogger(__name__)

# Tolerance for resource-counter comparisons. Fractional requests (PG
# bundles like {"CPU": 0.01}) are not exactly representable in binary
# floating point, so long allocate/credit churn leaves ~1e-13 dust per
# cycle in the availability counters; an exact >= would then starve
# whole-unit requests on an idle node.
_RES_EPS = 1e-9


class _Worker:
    __slots__ = ("worker_id", "address", "pid", "proc", "state", "lease_id",
                 "kind", "env_hash", "log_base")

    def __init__(self, worker_id, address, pid, proc, kind="cpu",
                 env_hash="", log_base=""):
        self.worker_id = worker_id
        self.address = address
        self.pid = pid
        self.proc = proc  # subprocess.Popen or None (external)
        self.state = "idle"  # idle | leased | dead
        self.lease_id: Optional[str] = None
        self.kind = kind  # "cpu" | "tpu"
        self.log_base = log_base  # stdout/.err capture path prefix
        # Pool is keyed by (kind, env_hash), the way the reference keys
        # its pool by language + runtime-env hash (worker_pool.h:280):
        # repeated use of one runtime env lands on warm workers that
        # already booted with it, and heterogeneous jobs never share a
        # process. "" = the default (no-env) pool. TPU workers keep the
        # accelerator runtime on their import path, CPU workers start
        # ~6x faster without it.
        self.env_hash = env_hash


class NodeAgent:
    def __init__(
        self,
        control_address: str,
        session_id: str,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        temp_dir: Optional[str] = None,
    ):
        self.node_id = NodeID.from_random()
        self.session_id = session_id
        self.control_address = control_address
        self._server = RpcServer("node_agent", host, port)
        self._server.register_instance(self)
        # raw (in-connection-order) handlers: a worker's oneway seal must
        # land before the recycle that chases it, and the recycle before
        # the next create_object, all on the same connection — dispatched
        # handlers would race and the create would miss the parked pages
        # every time in a put/delete loop. Both are lock-only (never
        # block), so inline execution in the read loop is safe.
        self._server.register_raw("seal_object", self._raw_seal_object)
        self._server.register_raw("recycle_object", self._raw_recycle_object)
        self._server.on_disconnect = self._owner_conn_closed

        from ray_tpu.accelerators import detect_node_resources_and_labels

        auto_res, auto_labels = detect_node_resources_and_labels()
        self.resources_total: Dict[str, float] = dict(auto_res)
        if resources:
            self.resources_total.update(resources)
        self.labels = {**auto_labels, **(labels or {})}

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.resources_available = dict(self.resources_total)
        # pg_id -> {"state": prepared|committed, "bundles": {idx: res},
        #            "available": {idx: res}}
        self._bundles: Dict[str, Dict[str, Any]] = {}

        self._workers: Dict[str, _Worker] = {}  # worker_id hex -> record
        self._leases: Dict[str, Dict[str, Any]] = {}  # lease_id -> info
        self._pending_spawns = 0
        # lease requests currently waiting for resources (the autoscaler's
        # demand signal, carried on heartbeats — reference: resource_load
        # in the syncer's node snapshots)
        self._pending_leases = 0
        # resource shapes recently starved for (shape key -> last seen):
        # heartbeats report entries younger than the TTL
        self._starved_shapes: Dict[tuple, float] = {}
        # short-TTL cluster-view cache for the spillback consult
        # (_pick_target_node) — one fetch serves a whole lease storm
        self._view_cache_lock = threading.Lock()
        self._view_cache: Tuple[float, Any] = (0.0, None)
        # versioned-sync counters (observability for the delta protocol)
        self._hb_full = 0
        self._hb_light = 0

        self.temp_dir = temp_dir or os.path.join(
            config.temp_dir, f"session_{session_id[:8]}"
        )
        os.makedirs(os.path.join(self.temp_dir, "logs"), exist_ok=True)

        self.store = ShmObjectStore(
            session_id,
            self.node_id.hex(),
            int(config.object_store_memory_mb) * 1024 * 1024,
        )

        from ray_tpu.core.ha import head_resolver

        self._control = RpcClient(
            control_address, name="agent->cs", resolver=head_resolver()
        )
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []
        # Data-plane listener (object transfer): whole segments stream
        # over a raw TCP socket via sendfile — the control RPC stack never
        # carries bulk object bytes (parity: reference object manager's
        # dedicated data port, src/ray/object_manager/object_manager.h).
        self._data_sock: Optional[socket.socket] = None
        self.data_port = 0
        # True when this agent is the whole process (node_main): being
        # declared dead exits the process; in-head agents just stop.
        self.standalone = False

    @property
    def address(self) -> str:
        return self._server.address

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._server.start()
        self._start_data_server()
        reply = self._control.call(
            "register_node",
            node_info={
                "node_id": self.node_id.hex(),
                "address": self.address,
                "resources_total": self.resources_total,
                "labels": self.labels,
                "object_store_capacity": self.store.usage()[1],
            },
            retryable=True,
        )
        config.load_snapshot(reply["config_snapshot"])
        # Session-scoped crash dir: this process's faulthandler + black
        # box re-point here, and spawned workers inherit it via
        # RT_CRASH_DIR (boot crashes landed in the temp_dir default).
        os.environ["RT_CRASH_DIR"] = os.path.join(self.temp_dir, "crash")
        forensics.install(forensics.current_role() or "driver")
        profiler.maybe_start_continuous()
        t = threading.Thread(target=self._heartbeat_loop, name="agent-hb", daemon=True)
        t.start()
        self._threads.append(t)
        tm = threading.Thread(
            target=self._memory_monitor_loop, name="agent-oom", daemon=True
        )
        tm.start()
        self._threads.append(tm)
        for _ in range(int(config.worker_pool_prestart)):
            self._spawn_worker()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
        for w in workers:
            self._terminate_worker(w)
        if self._data_sock is not None:
            try:
                # wake any thread blocked in accept(2) — close alone
                # leaves it parked on a reusable fd number (see
                # RpcServer.stop)
                self._data_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._data_sock.close()
            except OSError:
                pass
        self._server.stop()
        self._control.close()
        self.store.shutdown()

    # ------------------------------------------------------------------
    # data plane: whole-segment streaming over a raw TCP port (parity:
    # reference object manager's dedicated data port + chunked transfer,
    # src/ray/object_manager/object_manager.h — here one request streams
    # the whole segment via sendfile; native/src/store_core.cpp pumps it,
    # os.sendfile is the fallback)
    # ------------------------------------------------------------------

    _DATA_LOST = 0xFFFFFFFFFFFFFFFF

    def _start_data_server(self) -> None:
        try:
            host = self.address.rsplit(":", 1)[0]
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, 0))
            sock.listen(64)
        except OSError as e:
            # port-restricted environment: the node stays fully functional
            # on the chunked-RPC path (data_port=0 advertises exactly that)
            logger.warning("data-plane listener unavailable: %s", e)
            self.data_port = 0
            return
        self._data_sock = sock
        self.data_port = sock.getsockname()[1]
        t = threading.Thread(
            target=self._data_accept_loop, name="agent-data", daemon=True
        )
        t.start()
        self._threads.append(t)

    def _data_accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._data_sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_data_conn, args=(conn,),
                name="agent-data-conn", daemon=True,
            ).start()

    def _serve_data_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hdr = self._recv_exact(conn, 4)
            if hdr is None:
                return
            (path_len,) = struct.unpack("<I", hdr)
            if path_len > 4096:
                return
            req = self._recv_exact(conn, path_len + 16)
            if req is None:
                return
            path = req[:path_len].decode()
            offset, length = struct.unpack("<QQ", req[path_len:])
            try:
                opened = self.store.open_for_read(path)
            except ValueError:
                opened = None
            if opened is None:
                conn.sendall(struct.pack("<Q", self._DATA_LOST))
                return
            fd, size = opened
            try:
                if offset >= size:
                    conn.sendall(struct.pack("<Q", 0))
                    return
                total = min(length, size - offset)
                conn.sendall(struct.pack("<Q", total))
                from ray_tpu import native as native_mod

                lib = native_mod.store_lib()
                if lib is not None:
                    sent = lib.rt_sendfile_full(
                        conn.fileno(), fd, offset, total
                    )
                    if sent != total:
                        return  # peer gone or file truncated: drop conn
                else:
                    off = offset
                    remaining = total
                    while remaining > 0:
                        n = os.sendfile(
                            conn.fileno(), fd, off, min(remaining, 1 << 22)
                        )
                        if n <= 0:
                            return
                        off += n
                        remaining -= n
            finally:
                os.close(fd)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            part = conn.recv(n - len(buf))
            if not part:
                return None
            buf += part
        return buf

    def rpc_get_data_port(self, conn):
        return self.data_port

    def _update_pool_gauge_locked(self) -> None:
        """Refresh rt_worker_pool_size{state=...,node=...} from the live
        pool."""
        if not core_metrics.ENABLED:
            return
        counts: Dict[str, int] = {"idle": 0, "leased": 0, "dead": 0}
        for w in self._workers.values():
            counts[w.state] = counts.get(w.state, 0) + 1
        counts["spawning"] = self._pending_spawns
        node = self.node_id.hex()[:8]
        for state, n in counts.items():
            core_metrics.worker_pool_size.set(
                n, tags={"state": state, "node": node}
            )

    def _heartbeat_loop(self) -> None:
        # Versioned resource-view sync (reference ray_syncer.h:91 delta
        # protocol): a heartbeat carries the full resource payload only
        # when it CHANGED since the last acked beat (or as a periodic
        # anti-entropy refresh); unchanged beats are a light liveness ping
        # with the last version, so steady-state control-plane traffic is
        # O(nodes), not O(nodes x resource-dict size).
        last_sent = None
        version = 0
        since_full = 0
        while not self._stopped.wait(config.health_check_period_s):
            with self._lock:
                if core_metrics.ENABLED:
                    self._update_pool_gauge_locked()
                avail = dict(self.resources_available)
                pending = self._pending_leases
                busy = len(self._leases)
                now = time.monotonic()
                for k, ts in list(self._starved_shapes.items()):
                    if now - ts > 5.0:
                        del self._starved_shapes[k]
                shapes = [dict(k) for k in self._starved_shapes]
            payload = (tuple(sorted(avail.items())), pending, busy,
                       tuple(tuple(sorted(s.items())) for s in shapes))
            unchanged = payload == last_sent and since_full < 30
            try:
                if unchanged:
                    since_full += 1
                    self._hb_light += 1
                    reply = self._control.call(
                        "heartbeat", node_id=self.node_id.hex(),
                        resources_available=None, timeout_s=5.0,
                        view_version=version,
                    )
                    if reply.get("reattach"):
                        # head restarted: re-assert our state (or die if
                        # the store has explicitly declared us dead)
                        if not self._reattach_to_head():
                            return
                        last_sent = None
                        continue
                    if reply.get("resync"):
                        last_sent = None  # store lost our view: full next
                    if not reply.get("ok"):
                        self._declared_dead()
                        return
                    continue
                version += 1
                since_full = 0
                self._hb_full += 1
                reply = self._control.call(
                    "heartbeat", node_id=self.node_id.hex(),
                    resources_available=avail, timeout_s=5.0,
                    pending_leases=pending, active_leases=busy,
                    extra={"pending_shapes": shapes}, view_version=version,
                )
                last_sent = payload
                if reply.get("reattach"):
                    if not self._reattach_to_head():
                        return
                    last_sent = None
                    continue
                if not reply.get("ok"):
                    self._declared_dead()
                    return
            except RpcError:
                # the beat may not have landed: resend a full view next
                last_sent = None

    def _declared_dead(self) -> None:
        """Declared dead by the control plane: our actors may already be
        restarting elsewhere. Tear down (killing all local workers) so no
        split-brain actor instance keeps serving (reference: raylets exit
        when GCS declares them dead)."""
        logger.warning("control store declared this node dead; shutting down")
        self.stop()
        if self.standalone:
            os._exit(1)

    def _reattach_to_head(self) -> bool:
        """Re-assert this node's full state with a restarted head (HA
        reconciliation; parity: raylet reconnect under GCS FT). Reports
        live leases (tagged owner-bound vs store-managed), committed PG
        bundles, and pooled workers; the store replies with orphaned
        store-managed leases to release. Returns False when the store
        refuses (we are declared dead) — the caller must exit."""
        with self._lock:
            leases = {
                lid: {"bound": info.get("conn_id") is not None}
                for lid, info in self._leases.items()
            }
            bundles = {
                pg_id: sorted(rec["bundles"])
                for pg_id, rec in self._bundles.items()
                if rec["state"] == "committed"
            }
            workers = [
                w.address for w in self._workers.values()
                if w.state != "dead"
            ]
            node_info = {
                "node_id": self.node_id.hex(),
                "address": self.address,
                "resources_total": dict(self.resources_total),
                "labels": dict(self.labels),
                "object_store_capacity": self.store.usage()[1],
            }
        try:
            reply = self._control.call(
                "reattach_node", node_info=node_info, leases=leases,
                bundles=bundles, workers=workers, retryable=True,
            )
        except RpcError:
            logger.warning("re-attach RPC failed; retrying on next beat")
            return True  # transient: keep heartbeating, reattach re-asked
        if not reply.get("ok"):
            self._declared_dead()
            return False
        config.load_snapshot(reply["config_snapshot"])
        self.control_address = self._control.address
        orphans = reply.get("release_leases") or []
        for lid in orphans:
            # store-managed leases no live actor references (the head died
            # mid-creation): kill the half-created worker so the actor's
            # reschedule cannot double-place
            try:
                self.rpc_release_worker(None, lid, kill=True)
            except Exception:  # noqa: BLE001 — cleanup path
                logger.exception("orphan lease %s release failed", lid[:8])
        logger.info(
            "re-attached to head at %s (%d leases kept, %d orphans "
            "released)", self._control.address, len(leases) - len(orphans),
            len(orphans),
        )
        return True

    # ------------------------------------------------------------------
    # memory monitor / OOM killer (reference C19: MemoryMonitor
    # src/ray/common/memory_monitor.h:56 + WorkerKillingPolicy
    # worker_killing_policy.h:33)
    # ------------------------------------------------------------------

    @staticmethod
    def _memory_usage_fraction() -> float:
        """Host memory usage in [0, 1]. Test hook: the
        testing_memory_usage config (>=0) overrides the real reading."""
        injected = float(config.testing_memory_usage)
        if injected >= 0:
            return injected
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", info.get("MemFree", 0))
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except (OSError, ValueError):
            return 0.0

    def _memory_monitor_loop(self) -> None:
        period = float(config.memory_monitor_period_s)
        threshold = float(config.memory_usage_threshold)
        while not self._stopped.wait(period):
            if self._memory_usage_fraction() < threshold:
                continue
            # Kill policy (reference worker_killing_policy: prefer
            # retriable / newest): the most recently LEASED worker — its
            # task is the newest work and the most likely to be retried
            # cleanly; idle pool workers are reaped first of all.
            victim = None
            with self._lock:
                idle = [w for w in self._workers.values() if w.state == "idle"]
                if idle:
                    victim = idle[0]
                    self._workers.pop(victim.worker_id, None)
                elif self._leases:
                    newest_lease = next(reversed(self._leases))
                    info = self._leases.get(newest_lease)
                    victim = self._workers.get(info["worker_id"]) if info else None
            if victim is not None:
                logger.warning(
                    "memory pressure (%.0f%% used >= %.0f%%): killing "
                    "worker pid=%s",
                    self._memory_usage_fraction() * 100, threshold * 100,
                    victim.pid,
                )
                self._terminate_worker(victim)

    # ------------------------------------------------------------------
    # worker pool (reference C6)
    # ------------------------------------------------------------------

    def _spawn_worker(self, kind: str = "cpu", env_spec=None,
                      env_hash: str = "", slot_reserved: bool = False) -> None:
        """slot_reserved: the caller already counted this spawn in
        _pending_spawns (under _lock, before the fork) so the spawn gate
        can't be double-passed during the ~100ms Popen window."""
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        pythonpath = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        if kind == "cpu":
            # Strip accelerator site hooks (they import jax at interpreter
            # startup — seconds of cold-start a CPU worker never needs).
            parts = [
                p for p in pythonpath.split(os.pathsep)
                if p and "axon_site" not in p
            ]
            pythonpath = os.pathsep.join(parts)
            env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = pythonpath
        env["RT_CONFIG_SNAPSHOT"] = config.snapshot()
        env["RT_CRASH_DIR"] = os.path.join(self.temp_dir, "crash")
        python = sys.executable
        if env_spec:
            # boot the worker INSIDE its runtime env: pip envs get the
            # env's interpreter; working_dir/py_modules/env_vars apply in
            # worker_main before the worker registers (reference: the
            # runtime-env agent prepares the env, then the pool forks the
            # worker into it)
            from ray_tpu.core import runtime_env as runtime_env_mod

            if env_spec.get("pip"):
                python = runtime_env_mod.ensure_pip_env(env_spec["pip"])
            import base64

            from ray_tpu.utils import serialization

            env["RT_BOOT_ENV"] = base64.b64encode(
                serialization.dumps(env_spec)
            ).decode()
        log_base = os.path.join(self.temp_dir, "logs", f"worker-{uuid.uuid4().hex[:8]}")
        stdout = open(log_base + ".out", "wb")
        stderr = open(log_base + ".err", "wb")
        proc = subprocess.Popen(
            [
                python, "-m", "ray_tpu.core.worker_main",
                "--node-address", self.address,
                "--control-address", self.control_address,
                "--node-id", self.node_id.hex(),
                "--session-id", self.session_id,
                "--kind", kind,
                "--env-hash", env_hash,
            ],
            env=env, stdout=stdout, stderr=stderr, start_new_session=True,
        )
        stdout.close()
        stderr.close()
        _PROC_REGISTRY[proc.pid] = proc
        _PROC_LOGS[proc.pid] = log_base
        if not slot_reserved:
            with self._lock:
                self._pending_spawns += 1
        threading.Thread(
            target=self._reap_worker, args=(proc,), name="agent-reap", daemon=True
        ).start()

    def _reap_worker(self, proc: subprocess.Popen) -> None:
        proc.wait()
        dead: Optional[_Worker] = None
        if _PROC_REGISTRY.pop(proc.pid, None) is not None:
            # Died before ever registering: release the spawn slot.
            _PROC_LOGS.pop(proc.pid, None)
            with self._lock:
                self._pending_spawns = max(0, self._pending_spawns - 1)
                self._cv.notify_all()
        freed_lease = False
        with self._lock:
            for w in self._workers.values():
                if w.proc is proc:
                    dead = w
                    break
            if dead is not None:
                self._workers.pop(dead.worker_id, None)
                if dead.state == "leased" and dead.lease_id in self._leases:
                    info = self._leases.pop(dead.lease_id)
                    self._release_resources_locked(info)
                    freed_lease = True
                dead.state = "dead"
                self._cv.notify_all()
        if dead is not None and not self._stopped.is_set():
            if freed_lease:
                # only a leased worker's death frees capacity; an idle
                # worker crash-looping must not spam cluster-wide kicks
                self._notify_capacity_freed()
            try:
                self._control.call_oneway(
                    "report_worker_failure",
                    worker_address=dead.address,
                    node_id=self.node_id.hex(),
                    reason=f"worker process exited with code {proc.returncode}",
                )
            except RpcError:
                pass

    def rpc_register_worker(self, conn, worker_id: str, address: str, pid: int,
                            kind: str = "cpu", env_hash: str = ""):
        with self._lock:
            self._pending_spawns = max(0, self._pending_spawns - 1)
            w = _Worker(worker_id, address, pid, _PROC_REGISTRY.pop(pid, None),
                        kind=kind, env_hash=env_hash,
                        log_base=_PROC_LOGS.pop(pid, ""))
            self._workers[worker_id] = w
            self._cv.notify_all()
        # a fresh idle worker unparks zero-wait lease retries just like
        # freed resources do
        self._notify_capacity_freed()
        return {"node_id": self.node_id.hex(), "session_id": self.session_id}

    def _terminate_worker(self, w: _Worker) -> None:
        try:
            os.kill(w.pid, 15)
        except (ProcessLookupError, PermissionError):
            pass

    # ------------------------------------------------------------------
    # leases (reference C4/C5: HandleRequestWorkerLease + cluster scheduler)
    # ------------------------------------------------------------------

    def rpc_lease_worker(
        self,
        conn,
        resources: Dict[str, float],
        bundle=None,
        strategy=None,
        wait_s: float = 30.0,
        bind_to_conn: bool = True,
        runtime_env=None,
        spillback: bool = True,
    ):
        """bind_to_conn: a lease granted to a driver/executor (the lease
        cache) dies with its owner's RPC connection — an owner that exits
        without releasing (crash, no shutdown()) must not strand leased
        workers forever. The control store passes False: actor leases are
        store-managed (actor death/restart flows release them), and a
        transient store->agent reconnect must NOT kill every actor on the
        node.

        spillback=False: the control store's actor scheduler already
        picked this node from the GLOBAL cluster view, so re-consulting
        the store here would only amplify load — a capacity-freed kick
        retries every parked actor at once, and thousands of lease
        requests each calling get_cluster_view back to the store queue
        ahead of everything else on the store's dispatcher (ISSUE 14:
        the 10k kill-drain stalled 30s exactly this way)."""
        resources = {k: float(v) for k, v in (resources or {}).items() if v}
        if core_metrics.ENABLED:
            core_metrics.lease_requests.inc()
        # Cluster-level decision: can/should this run here? (spillback)
        if bundle is None:
            if spillback:
                target = self._pick_target_node(resources, strategy)
            else:
                # store-scheduled: the caller already picked this node
                # from the global view — treat it as the target
                target = {"node_id": self.node_id.hex()}
            if target is not None and target["node_id"] != self.node_id.hex():
                return {"granted": False, "spillback": target["address"]}
            if target is None and not self._feasible_locally(resources):
                # No live node's TOTALS fit: surface the error to the
                # caller fast, but record the shape so the autoscaler can
                # report truly-infeasible demand in `rt status`
                with self._lock:
                    shape_key = tuple(
                        sorted((k, float(v)) for k, v in resources.items())
                    )
                    self._starved_shapes[shape_key] = time.monotonic()
                return {"granted": False, "error": "infeasible"}
        else:
            # Bundle pinned to a PG: if this node doesn't host the
            # *requested bundle index* (it may host other bundles of a
            # SPREAD PG), spill back to the node that does (control store
            # records bundle_locations at COMMIT) rather than timing out
            # forever locally.
            with self._lock:
                rec = self._bundles.get(bundle[0])
                req_idx = bundle[1]
                have_pg = rec is not None and (
                    req_idx is None or req_idx < 0 or req_idx in rec["bundles"]
                )
            if not have_pg:
                target = self._pick_bundle_node(bundle)
                if target == "pending":
                    # PG exists but hasn't committed anywhere yet — let the
                    # caller retry (same contract as a lease timeout).
                    return {"granted": False, "error": "lease timeout"}
                if target is not None and target["node_id"] != self.node_id.hex():
                    return {"granted": False, "spillback": target["address"]}
                if target is None:
                    return {"granted": False, "error": "bundle not found"}
        deadline = time.monotonic() + wait_s
        kind = "tpu" if resources.get("TPU") else "cpu"
        owner_conn = conn if (bind_to_conn and conn is not None) else None
        from ray_tpu.core import runtime_env as runtime_env_mod

        env_hash = runtime_env_mod.env_hash(runtime_env)
        return self._lease_wait(  # rtlint: ignore[dispatcher-block] the agent dispatch pool spawns per-request threads (never queues), so a parked lease holds no shared thread; slicing would double scheduler RPCs on the grant hot path
            resources, bundle, deadline, kind, strategy, owner_conn,
            runtime_env, env_hash,
        )

    def _lease_wait(self, resources, bundle, deadline, kind, strategy=None,
                    owner_conn=None, env_spec=None, env_hash=""):
        spawned_for_me = False
        starved = False  # counted toward autoscaler demand
        last_spill_check = time.monotonic()
        self._lock.acquire()
        try:
            while True:
                ok, resolved_bundle = self._try_allocate_locked(resources, bundle)
                if not ok and bundle is None and not starved:
                    # Resource-starved (NOT merely waiting on a worker
                    # spawn, and not bundle-pinned — a new node can't
                    # serve those): the autoscaler's demand signal.
                    starved = True
                    self._pending_leases += 1
                    # sticky per-SHAPE record: zero-wait scheduler retries
                    # make the counter flicker faster than heartbeats
                    # sample, but the shape entry survives (TTL-reported)
                    # so the autoscaler can bin-pack real demand
                    shape_key = tuple(
                        sorted((k, float(v)) for k, v in resources.items())
                    )
                    self._starved_shapes[shape_key] = time.monotonic()
                if ok:
                    if owner_conn is not None and not owner_conn.alive:
                        # the owner disconnected while this request waited
                        # — its reap callback has already run, so a grant
                        # now would register an unreapable (stranded)
                        # lease
                        self._deallocate_locked(resources, resolved_bundle)
                        return {
                            "granted": False, "error": "owner disconnected",
                        }
                    worker = self._pop_idle_worker_locked(kind, env_hash)
                    if worker is not None:
                        lease_id = uuid.uuid4().hex
                        worker.state = "leased"
                        worker.lease_id = lease_id
                        self._leases[lease_id] = {
                            "resources": resources,
                            "bundle": resolved_bundle,
                            "worker_id": worker.worker_id,
                            "conn_id": (
                                id(owner_conn)
                                if owner_conn is not None else None
                            ),
                        }
                        # no re-check needed: we hold self._lock from the
                        # liveness check through this insert, and the reap
                        # scan (_owner_conn_closed) needs the same lock —
                        # a disconnect after the check reaps post-insert
                        if core_metrics.ENABLED:
                            core_metrics.lease_grants.inc()
                        return {
                            "granted": True,
                            "worker_address": worker.address,
                            "lease_id": lease_id,
                            "node_id": self.node_id.hex(),
                        }
                    # Resources ok but no idle worker: undo the allocation,
                    # ensure a spawn is in flight for this request, wait.
                    # Capacity cap: short zero-wait lease retries (the
                    # control-store scheduler queue) must not each spawn
                    # their own worker — the pool never needs more workers
                    # of a kind than the node can concurrently lease.
                    self._deallocate_locked(resources, resolved_bundle)
                    if not spawned_for_me:
                        spawned_for_me = True
                        res_key = "TPU" if kind == "tpu" else "CPU"
                        cap = max(1, int(self.resources_total.get(res_key, 1)))
                        n_kind = sum(
                            1 for w in self._workers.values()
                            if w.kind == kind and w.state != "dead"
                        )
                        evicted = None
                        if n_kind + self._pending_spawns >= cap:
                            # at capacity with idle workers of another
                            # runtime env: evict one to make room
                            evicted = self._evict_idle_mismatch_locked(
                                kind, env_hash
                            )
                        # pending_spawns == 0 always allows a spawn: the
                        # demand DID fit the resources (ok was True), so
                        # zero/fractional-CPU requests past the capacity
                        # cap must still make progress — the cap only
                        # throttles CONCURRENT spawns from retry storms.
                        # Zero-wait requests (the store scheduler's
                        # fire-and-forget retries) can never use their own
                        # spawn — it is purely a spawn-AHEAD for a later
                        # retry — so they slow-start (at most max(2,
                        # n_kind) in flight, doubling as workers register)
                        # instead of fork-bombing up to cap at once: after
                        # a mass kill, the straggler retries of
                        # already-dead actors otherwise spawn a full
                        # pool's worth of workers nobody will use, and
                        # the fork storm convoys every other RPC on the
                        # node (PG prepares, lease releases) for seconds
                        limit = cap
                        if deadline <= time.monotonic():
                            limit = min(cap, max(2, n_kind))
                        if evicted is not None or self._pending_spawns == 0 or (
                            n_kind + self._pending_spawns < limit
                        ):
                            # reserve the slot BEFORE dropping the lock:
                            # the fork takes ~100ms and an unreserved
                            # gate would let every concurrently-parked
                            # request pass it in that window
                            self._pending_spawns += 1
                            spawned = False
                            self._lock.release()
                            try:
                                if evicted is not None:
                                    self._terminate_worker(evicted)
                                self._spawn_worker(
                                    kind, env_spec, env_hash,
                                    slot_reserved=True,
                                )
                                spawned = True
                            finally:
                                self._lock.acquire()
                                if not spawned:
                                    self._pending_spawns = max(
                                        0, self._pending_spawns - 1
                                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"granted": False, "error": "lease timeout"}
                # A queued lease must notice capacity that appears
                # ELSEWHERE (an autoscaler-launched node): periodically
                # re-consult the cluster view — WITH the original strategy
                # (hard affinity must not be hijacked) — and spill only to
                # a node that actually has the resources AVAILABLE (a
                # feasible-by-total-but-full node would just bounce the
                # lease back and forth until the hop cap kills the task).
                if (
                    not ok
                    and bundle is None
                    and time.monotonic() - last_spill_check > 1.0
                ):
                    last_spill_check = time.monotonic()
                    self._lock.release()
                    try:
                        target = self._pick_available_target(
                            resources, strategy
                        )
                    finally:
                        self._lock.acquire()
                    if (
                        target is not None
                        and target["node_id"] != self.node_id.hex()
                    ):
                        return {
                            "granted": False,
                            "spillback": target["address"],
                        }
                self._cv.wait(min(remaining, 0.5))
        finally:
            if starved:
                self._pending_leases -= 1
            self._lock.release()

    def _pick_available_target(self, resources, strategy):
        """Like _pick_target_node, but only returns nodes whose AVAILABLE
        resources fit the request (used by the mid-wait re-spill)."""
        try:
            view = self._control.call("get_cluster_view", timeout_s=5.0)
        except RpcError:
            return None
        node_id = scheduling.pick_node(
            view, resources, strategy, local_node_id=self.node_id.hex()
        )
        if node_id is None or node_id not in view:
            return None
        avail = view[node_id].get("resources_available", {})
        if not all(avail.get(k, 0.0) >= v for k, v in resources.items() if v > 0):
            return None
        return {"node_id": node_id, "address": view[node_id]["address"]}

    def _owner_conn_closed(self, conn) -> None:
        """An RPC client disconnected: reap every conn-bound lease it
        held (reference: raylet disconnects kill the worker leases of a
        dead owner). kill=True — the worker may be mid-task for the dead
        owner; a poisoned warm worker is worse than a respawn."""
        conn_id = id(conn)
        with self._lock:
            dead = [
                lid for lid, info in self._leases.items()
                if info.get("conn_id") == conn_id
            ]
        for lid in dead:
            try:
                self.rpc_release_worker(None, lid, kill=True)
            except Exception:  # noqa: BLE001 — teardown path
                logger.exception("lease %s reap failed", lid[:8])

    def rpc_release_worker(self, conn, lease_id: str, kill: bool = False):
        with self._lock:
            info = self._leases.pop(lease_id, None)
            if info is None:
                return False
            self._release_resources_locked(info)
            worker = self._workers.get(info["worker_id"])
            if worker is not None:
                if kill:
                    self._workers.pop(worker.worker_id, None)
                else:
                    worker.state = "idle"
                    worker.lease_id = None
            self._cv.notify_all()
        if kill and worker is not None:
            self._terminate_worker(worker)
        self._notify_capacity_freed()
        return True

    def rpc_release_workers(self, conn, lease_ids: List[str],
                            kill: bool = False):
        """Bulk lease release (ISSUE 14 kill-drain): one lock pass frees
        every lease's resources, workers terminate outside the lock, and
        the whole batch sends ONE capacity kick instead of one per lease.
        Returns the number of leases actually released (unknown ids are
        skipped — releases are idempotent)."""
        released = 0
        doomed_workers = []
        with self._lock:
            for lease_id in lease_ids:
                info = self._leases.pop(lease_id, None)
                if info is None:
                    continue
                released += 1
                self._release_resources_locked(info)
                worker = self._workers.get(info["worker_id"])
                if worker is not None:
                    if kill:
                        self._workers.pop(worker.worker_id, None)
                        doomed_workers.append(worker)
                    else:
                        worker.state = "idle"
                        worker.lease_id = None
            if released:
                self._cv.notify_all()
        for worker in doomed_workers:
            self._terminate_worker(worker)
        if released:
            self._notify_capacity_freed()
        return released

    def _release_resources_locked(self, info: Dict[str, Any]) -> None:
        self._deallocate_locked(info["resources"], info["bundle"])

    def _notify_capacity_freed(self) -> None:
        """Tell the store capacity freed here so pending actors/PGs retry
        NOW instead of waiting out their (up to 2s) scheduler backoff.
        Debounced: a burst of releases sends one kick per 50ms."""
        now = time.monotonic()
        if now - getattr(self, "_last_free_notify", 0.0) < 0.05:
            return
        self._last_free_notify = now
        try:
            self._control.call_oneway(
                "capacity_freed", node_id=self.node_id.hex()
            )
        except RpcError:
            pass  # heartbeat anti-entropy covers the lost kick

    @staticmethod
    def _fits(pool, need) -> bool:
        """Epsilon-tolerant resource fit: repeated fractional
        allocate/credit cycles (e.g. 400 PG carve-outs of 0.01 CPU)
        leave float dust in the availability counters, and an exact >=
        would then refuse a whole-CPU request forever on a node that is
        arithmetically idle."""
        return all(
            pool.get(k, 0.0) >= v - _RES_EPS for k, v in need.items()
        )

    def _credit_main_locked(self, resources) -> None:
        """Credit the node pool, snapping each counter back to the node
        total when it lands within epsilon — the dust from fractional
        churn must not accumulate across workload generations."""
        for k, v in resources.items():
            avail = self.resources_available.get(k, 0.0) + v
            total = self.resources_total.get(k, 0.0)
            if abs(avail - total) < 1e-6:
                avail = total
            self.resources_available[k] = avail

    def _try_allocate_locked(self, resources, bundle):
        """Returns (ok, resolved_bundle). resolved_bundle pins the concrete
        pool index an index=-1 bundle request landed in, so release returns
        capacity to the exact pool it came from."""
        if bundle is not None:
            pg_id, idx = bundle
            rec = self._bundles.get(pg_id)
            if rec is None or rec["state"] != "committed":
                return False, None
            pool_idx = self._bundle_pool_index(rec, idx, resources)
            if pool_idx is None:
                return False, None
            pool = rec["available"][pool_idx]
            for k, v in resources.items():
                pool[k] = pool.get(k, 0.0) - v
            return True, (pg_id, pool_idx)
        if not self._fits(self.resources_available, resources):
            return False, None
        for k, v in resources.items():
            left = self.resources_available.get(k, 0.0) - v
            # the epsilon fit may leave -1e-12 dust; never go negative
            self.resources_available[k] = left if left > 0.0 else 0.0
        return True, None

    def _bundle_pool_index(self, rec, idx, resources) -> Optional[int]:
        if idx is not None and idx >= 0:
            pool = rec["available"].get(idx)
            if pool is not None and self._fits(pool, resources):
                return idx
            return None
        for i, pool in sorted(rec["available"].items()):
            if self._fits(pool, resources):
                return i
        return None

    def _deallocate_locked(self, resources, bundle) -> None:
        if bundle is not None:
            # bundle is always the allocation-resolved (pg_id, pool_idx)
            # pair — _try_allocate_locked pins the concrete pool, so credit
            # goes back exactly where it came from.
            pg_id, pool_idx = bundle
            rec = self._bundles.get(pg_id)
            if rec is None:
                return
            pool = rec["available"].setdefault(pool_idx, {})
            for k, v in resources.items():
                pool[k] = pool.get(k, 0.0) + v
            return
        self._credit_main_locked(resources)

    def _pop_idle_worker_locked(self, kind: str = "cpu",
                                env_hash: str = "") -> Optional[_Worker]:
        for w in self._workers.values():
            if (
                w.state == "idle" and w.kind == kind
                and w.env_hash == env_hash
            ):
                return w
        return None

    def _evict_idle_mismatch_locked(self, kind: str,
                                    env_hash: str) -> Optional[_Worker]:
        """An idle worker of the right kind but the WRONG runtime env:
        evictable to make room under the kind capacity cap (reference:
        the pool kills idle workers when a differently-env'd lease needs
        the slot)."""
        for w in self._workers.values():
            if (
                w.state == "idle" and w.kind == kind
                and w.env_hash != env_hash
            ):
                self._workers.pop(w.worker_id, None)
                w.state = "dead"
                return w
        return None

    def _feasible_locally(self, resources) -> bool:
        return all(
            self.resources_total.get(k, 0.0) >= v for k, v in resources.items()
        )

    def _pick_bundle_node(self, bundle):
        """Resolve which node hosts a PG bundle via the control store."""
        pg_id, idx = bundle
        try:
            pg = self._control.call("get_placement_group", pg_id=pg_id)
            view = self._control.call("get_cluster_view", timeout_s=5.0)
        except RpcError:
            # Transient control-store failure must not become a permanent
            # "bundle not found" for a healthy PG — have the caller retry.
            return "pending"
        if not pg:
            return None
        if pg.get("state") == "REMOVED":
            return None  # removed PG must error out, not retry forever
        locs = pg.get("bundle_locations") or {}
        if not locs:
            return "pending"
        node_id = None
        if idx is not None and idx >= 0:
            node_id = locs.get(idx, locs.get(str(idx)))
        elif locs:
            node_id = next(iter(locs.values()))
        if node_id is None:
            return None
        if node_id not in view:
            # Bundle host absent from the alive-node view: either a
            # heartbeat blip or a real death (in which case the control
            # store re-places the PG, _mark_node_dead). Either way the
            # right answer is "retry", not a permanent "bundle not found".
            return "pending"
        return {"node_id": node_id, "address": view[node_id]["address"]}

    def _pick_target_node(self, resources, strategy):
        """Cluster view consult for spillback (reference hybrid policy).
        The view is cached for a beat: a task-submission storm funnels
        every lease request through this consult, and re-fetching the
        view per request turns one storm into a second one aimed at the
        control store. Spillback targets computed on a ≤100 ms-stale
        view are already racy by nature (the view is a snapshot); a
        wrong pick costs one extra hop."""
        now = time.monotonic()
        with self._view_cache_lock:
            ts, cached = self._view_cache
            view = cached if now - ts < 0.1 else None
        if view is None:
            try:
                view = self._control.call("get_cluster_view", timeout_s=5.0)
            except RpcError:
                return None
            with self._view_cache_lock:
                self._view_cache = (now, view)
        node_id = scheduling.pick_node(
            view, resources, strategy, local_node_id=self.node_id.hex()
        )
        if node_id is None:
            return None
        return {"node_id": node_id, "address": view[node_id]["address"]}

    # ------------------------------------------------------------------
    # placement-group bundles (reference C3 raylet side: 2PC)
    # ------------------------------------------------------------------

    def rpc_prepare_bundles(self, conn, pg_id: str, bundles: Dict[int, Dict[str, float]]):
        with self._lock:
            bundles = {int(i): dict(b) for i, b in bundles.items()}
            existing = self._bundles.get(pg_id)
            if existing is not None:
                if existing["state"] == "prepared":
                    # Idempotent retry only if it's the same reservation; a
                    # record with a different bundle set must NOT be
                    # resurrected.
                    return existing["bundles"] == bundles
                # Committed record: a PG re-placement after node death may
                # land the lost bundles on a node already hosting surviving
                # bundles. Stage the new indices; commit merges them.
                staged = existing.get("staged") or {}
                if staged:
                    return staged == bundles  # idempotent retry
                if any(i in existing["bundles"] for i in bundles):
                    return False  # overlaps committed indices: invalid add
                need: Dict[str, float] = {}
                for b in bundles.values():
                    for k, v in b.items():
                        need[k] = need.get(k, 0.0) + v
                if not self._fits(self.resources_available, need):
                    return False
                for k, v in need.items():
                    left = self.resources_available.get(k, 0.0) - v
                    self.resources_available[k] = left if left > 0.0 else 0.0
                existing["staged"] = bundles
                return True
            need = {}
            for b in bundles.values():
                for k, v in b.items():
                    need[k] = need.get(k, 0.0) + v
            if not self._fits(self.resources_available, need):
                return False
            for k, v in need.items():
                left = self.resources_available.get(k, 0.0) - v
                self.resources_available[k] = left if left > 0.0 else 0.0
            self._bundles[pg_id] = {
                "state": "prepared",
                "bundles": {i: dict(b) for i, b in bundles.items()},
                "available": {i: dict(b) for i, b in bundles.items()},
            }
            return True

    def rpc_commit_bundles(self, conn, pg_id: str):
        with self._lock:
            rec = self._bundles.get(pg_id)
            if rec is None:
                return False
            for i, b in (rec.pop("staged", None) or {}).items():
                rec["bundles"][i] = dict(b)
                rec["available"][i] = dict(b)
            rec["state"] = "committed"
            self._cv.notify_all()
            return True

    def rpc_return_bundles(self, conn, pg_id: str, idxs: Optional[List[int]] = None):
        """Return bundle reservations to the node pool.

        idxs=None: full teardown (PG removed / total rollback). idxs given:
        partial rollback of a re-placement — only those bundle indices are
        returned (committed or staged), surviving bundles keep running.
        Any lease granted against a returned bundle is void — the worker
        holding it is killed and its caller retries against the re-placed
        PG (the reference likewise kills workers using removed bundles).
        """
        doomed = []
        with self._lock:
            rec = self._bundles.get(pg_id)
            if rec is None:
                return True
            staged = rec.get("staged") or {}
            if idxs is None:
                idx_set = set(rec["bundles"]) | set(staged)
            else:
                idx_set = {int(i) for i in idxs}
            for lease_id, info in list(self._leases.items()):
                b = info.get("bundle")
                if b and b[0] == pg_id and b[1] in idx_set:
                    self._leases.pop(lease_id, None)
                    w = self._workers.pop(info["worker_id"], None)
                    if w is not None:
                        doomed.append(w)
            for i in idx_set:
                spec = rec["bundles"].pop(i, None) or staged.pop(i, None)
                if spec is None:
                    continue
                rec["available"].pop(i, None)
                self._credit_main_locked(spec)
            if not rec["bundles"] and not staged:
                self._bundles.pop(pg_id, None)
            self._cv.notify_all()
        for w in doomed:
            self._terminate_worker(w)
        return True

    # ------------------------------------------------------------------
    # object store host (reference C7)
    # ------------------------------------------------------------------

    def rpc_create_object(self, conn, oid_hex: str, size: int):
        return self.store.create(oid_hex, size)

    def rpc_seal_object(self, conn, oid_hex: str):
        self.store.seal(oid_hex)
        return True

    def rpc_get_object_meta(self, conn, oid_hex: str, timeout_s: Optional[float] = None):
        return self.store.get_meta(oid_hex, timeout_s)

    def rpc_object_contains(self, conn, oid_hex: str):
        return self.store.contains(oid_hex)

    def rpc_delete_objects(self, conn, oid_hexes: List[str]):
        for h in oid_hexes:
            self.store.delete(h)
        return True

    def rpc_store_usage(self, conn):
        return self.store.usage()

    def _raw_seal_object(self, conn, req_id, args, kwargs):
        oid_hex = kwargs.get("oid_hex") or args[0]
        self.store.seal(oid_hex)
        RpcServer.reply(conn, req_id, True, True)

    def _raw_recycle_object(self, conn, req_id, args, kwargs):
        """Owner says: delete this never-shared object, recycling its
        segment pages into the pool (ShmObjectStore.recycle). Fast path
        runs inline in the connection read loop (lock-only, no blocking);
        an entry caught mid-spill/restore falls back to a threaded
        delete, which waits the move out."""
        oid_hex = kwargs.get("oid_hex") or args[0]
        if not self.store.recycle(oid_hex):
            threading.Thread(
                target=lambda: self.store.delete(oid_hex),
                name="agent-recycle-fallback", daemon=True,
            ).start()
        RpcServer.reply(conn, req_id, True, True)

    def rpc_read_object_chunk(self, conn, path: str, offset: int, length: int):
        """Serve a byte range of a local segment to a cross-node puller
        (reference C8: push_manager.h chunked transfer). The chunk rides
        the reply as a raw wire segment (serialization.Frame), not an
        in-band pickle copy."""
        chunk = self.store.read_chunk(path, offset, length)
        return None if chunk is None else serialization.maybe_frame(chunk)

    # ------------------------------------------------------------------
    # introspection (state API backing)
    # ------------------------------------------------------------------

    def rpc_list_objects(self, conn):
        """Object-store inventory for `state.objects()` / `rt memory`."""
        return {
            "node_id": self.node_id.hex(),
            "objects": self.store.inventory(),
        }

    def rpc_tail_worker_logs(self, conn, tail_bytes: int = 4096):
        """Tails of every worker's captured stdout/stderr on this node
        (`state.worker_logs()` / `rt logs`) — how a `print()` inside a
        task reaches the driver machine. Covers dead workers too: the
        files outlive the process."""
        tail_bytes = max(0, min(int(tail_bytes), 1 << 20))
        with self._lock:
            live = {
                os.path.basename(w.log_base): {
                    "worker_id": wid, "pid": w.pid, "state": w.state,
                }
                for wid, w in self._workers.items() if w.log_base
            }
        logs = []
        log_dir = os.path.join(self.temp_dir, "logs")
        try:
            names = sorted(os.listdir(log_dir))
        except OSError:
            names = []
        for fname in names:
            base, dot, ext = fname.rpartition(".")
            if ext not in ("out", "err") or not base.startswith("worker-"):
                continue
            path = os.path.join(log_dir, fname)
            try:
                size = os.path.getsize(path)
                with open(path, "rb") as f:
                    if size > tail_bytes:
                        f.seek(size - tail_bytes)
                    data = f.read(tail_bytes)
            except OSError:
                continue
            entry = {
                "node_id": self.node_id.hex(),
                "file": fname,
                "stream": "stdout" if ext == "out" else "stderr",
                "size": size,
                "tail": data.decode(errors="replace"),
            }
            entry.update(live.get(base, {}))
            logs.append(entry)
        # crash artifacts (faulthandler files + black boxes) surface
        # through the same listing — they too outlive their process
        crash_d = os.path.join(self.temp_dir, "crash")
        try:
            crash_names = sorted(os.listdir(crash_d))
        except OSError:
            crash_names = []
        for fname in crash_names:
            if fname.startswith("crash-"):
                stream = "crash"
            elif fname.startswith("blackbox-") and fname.endswith(".json"):
                stream = "blackbox"
            else:
                continue
            path = os.path.join(crash_d, fname)
            try:
                size = os.path.getsize(path)
                with open(path, "rb") as f:
                    if size > tail_bytes:
                        f.seek(size - tail_bytes)
                    data = f.read(tail_bytes)
            except OSError:
                continue
            logs.append({
                "node_id": self.node_id.hex(),
                "file": fname,
                "stream": stream,
                "size": size,
                "tail": data.decode(errors="replace"),
            })
        return logs

    def rpc_profile(self, conn, duration_s: float = 5.0,
                    hz: float = 99.0):
        """Sample this agent process's threads. The caller-supplied
        duration is capped so a profile RPC can hold a dispatcher
        thread for at most profiler_max_duration_s."""
        duration_s = min(
            float(duration_s), float(config.profiler_max_duration_s)
        )
        return profiler.capture(duration_s=duration_s, hz=hz)

    def rpc_stack_dump(self, conn):
        """All-thread stacks from this agent (hang forensics)."""
        return forensics.all_thread_stacks()

    def rpc_crash_reports(self, conn, pid: Optional[int] = None):
        """Crash artifacts on this node — black boxes + faulthandler
        files, dead workers included (`rt postmortem`)."""
        return {
            "node_id": self.node_id.hex(),
            "reports": forensics.list_crash_reports(
                dirs=[os.path.join(self.temp_dir, "crash")], pid=pid
            ),
        }

    def rpc_get_metrics(self, conn):
        """This process's metric registry (lease/pool/object-store series
        for a standalone agent; on the head this is the same registry the
        driver serves — state.cluster_metrics dedups by token)."""
        from ray_tpu.utils import metrics as metrics_mod

        return {
            "token": metrics_mod.PROCESS_TOKEN,
            "metrics": metrics_mod.snapshot_all(),
        }

    def rpc_get_state(self, conn):
        with self._lock:
            if core_metrics.ENABLED:
                self._update_pool_gauge_locked()
            return {
                "node_id": self.node_id.hex(),
                "address": self.address,
                "resources_total": dict(self.resources_total),
                "resources_available": dict(self.resources_available),
                "labels": dict(self.labels),
                "workers": {
                    wid: {"address": w.address, "pid": w.pid, "state": w.state}
                    for wid, w in self._workers.items()
                },
                "leases": {lid: dict(i) for lid, i in self._leases.items()},
                "bundles": {
                    pg: {"state": r["state"], "bundles": r["bundles"]}
                    for pg, r in self._bundles.items()
                },
                "store_usage": self.store.usage(),
                "spill_stats": self.store.spill_stats(),
                "heartbeat_stats": {
                    "full": self._hb_full, "light": self._hb_light,
                },
            }


_PROC_REGISTRY: Dict[int, subprocess.Popen] = {}
_PROC_LOGS: Dict[int, str] = {}
