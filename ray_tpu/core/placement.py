"""Placement groups — gang reservation of resource bundles.

Parity: python/ray/util/placement_group.py:126 (placement_group API) and
python/ray/util/scheduling_strategies.py:17 (PlacementGroupSchedulingStrategy).
The 2PC reservation itself lives in the control store
(control_store._schedule_pg) and node agents (prepare/commit bundles),
mirroring gcs_placement_group_scheduler.h:115-117.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.exceptions import PlacementGroupError
from ray_tpu.utils import serialization
from ray_tpu.utils.config import config
from ray_tpu.utils.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

_TERMINAL_PG_STATES = ("CREATED", "REMOVED")


def _wait_pg(control, pg_id: str, deadline: float):
    """Re-issue sliced wait_placement_group calls until the PG reaches a
    terminal state or the deadline passes.  The server caps each call at
    dispatch_wait_slice_s (it never holds a dispatcher thread for our
    whole deadline), so one long wait is a client-side loop now."""
    slice_s = float(config.dispatch_wait_slice_s)
    while True:
        info = control.call(
            "wait_placement_group", pg_id=pg_id, wait_s=slice_s,
            timeout_s=slice_s + 30.0,
        )
        if info is None or info.get("state") in _TERMINAL_PG_STATES:
            return info
        if time.monotonic() >= deadline:
            return info


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id_hex = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def ready(self):
        """Return an ObjectRef that resolves to this PG once created
        (parity: pg.ready() usable with ray.get)."""
        from ray_tpu.core import worker as worker_mod
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.utils.ids import ObjectID, TaskID

        w = worker_mod.global_worker()
        task_id = TaskID.for_normal_task(w.current_job_id())
        oid = ObjectID.from_task(task_id, 0)
        ref = ObjectRef(oid, w.address)

        def waiter():
            info = _wait_pg(
                w.control, self.id_hex, time.monotonic() + 3600.0
            )
            if info and info.get("state") == "CREATED":
                w.memory_store.put(oid, serialization.pack(self))
            else:
                w.memory_store.put(
                    oid,
                    PlacementGroupError(
                        f"placement group {self.id_hex[:8]} not created: "
                        f"{info.get('state') if info else 'missing'}"
                    ),
                )

        threading.Thread(target=waiter, daemon=True).start()
        return ref

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        info = _wait_pg(
            w.control, self.id_hex, time.monotonic() + timeout_seconds
        )
        return bool(info and info.get("state") == "CREATED")

    def table(self) -> Optional[Dict[str, Any]]:
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker()
        return w.control.call("get_placement_group", pg_id=self.id_hex)

    def __reduce__(self):
        return (PlacementGroup, (self.id_hex, self.bundles, self.strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"invalid strategy {strategy!r}; expected one of {VALID_STRATEGIES}"
        )
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker()
    pg_id = PlacementGroupID.from_random().hex()
    bundles = [{k: float(v) for k, v in b.items()} for b in bundles]
    w.control.call(
        "create_placement_group",
        pg_id=pg_id, bundles=bundles, strategy=strategy, name=name,
        job_id=w.current_job_id().hex(),
        retryable=True,
    )
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker()
    w.control.call("remove_placement_group", pg_id=pg.id_hex)


def placement_group_table() -> List[Dict[str, Any]]:
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker()
    return w.control.call("list_placement_groups")


class PlacementGroupSchedulingStrategy:
    """Parity: ray.util.scheduling_strategies.PlacementGroupSchedulingStrategy."""

    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks
