"""Cluster state API.

Parity: ray.util.state (reference python/ray/util/state/api.py) + the
`ray timeline` exporter (scripts.py:2171): list nodes/actors/jobs/
placement groups/workers/tasks, aggregate metrics, and dump a
Chrome-trace timeline of task execution events collected from every
worker's event buffer.

Functions accept an explicit control-store address, or use the connected
runtime's when omitted.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_tpu.utils.rpc import ClientPool, RpcConnectionError, RpcError

# Pooled connections: the dashboard's 5s auto-refresh page renders several
# state calls per view — dialing and closing a fresh socket per call would
# hammer the control store.
_pool = ClientPool("state-api")


def _control(address: Optional[str]):
    if address is None:
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker_or_none()
        if w is None:
            raise RuntimeError(
                "not connected: pass address= or call ray_tpu.init() first"
            )
        address = w.control_address
    return _pool.get(address)


def _with_control(address, fn):
    return fn(_control(address))


def list_nodes(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(
        address, lambda c: c.call("get_nodes", alive_only=False)
    )


def list_actors(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_actors"))


def list_jobs(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_jobs"))


def list_placement_groups(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_placement_groups"))


def _agent_states(address: Optional[str]) -> List[Dict[str, Any]]:
    nodes = [n for n in list_nodes(address) if n.get("alive", True)]
    out = []
    for n in nodes:
        try:
            out.append(
                _pool.get(n["address"]).call("get_state", timeout_s=10.0)
            )
        except RpcConnectionError:
            _pool.drop(n["address"])  # dead connection: rebuild next time
        except RpcError:
            pass  # slow, not dead: dropping would break concurrent users
    return out


def list_workers(address: Optional[str] = None) -> List[Dict[str, Any]]:
    out = []
    for st in _agent_states(address):
        for wid, w in st.get("workers", {}).items():
            out.append({"worker_id": wid, "node_id": st["node_id"], **w})
    return out


def cluster_status(address: Optional[str] = None) -> Dict[str, Any]:
    """`rt status` summary: nodes, resources, stores, actors, jobs."""
    nodes = list_nodes(address)
    agents = _agent_states(address)
    actors = list_actors(address)
    infeasible = None
    try:
        raw = _control(address).call(
            "kv_get", ns="autoscaler", key="infeasible", timeout_s=5.0
        )
        if raw:
            rec = json.loads(bytes(raw).decode())
            if time.time() - rec.get("ts", 0) < 60.0:  # recent only
                infeasible = rec
    except Exception:  # noqa: BLE001 — status must not fail on extras
        pass
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for st in agents:
        for k, v in st["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in st["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v
    head_ha = None
    try:
        head_ha = _control(address).call("ha_status", timeout_s=5.0)
    except Exception:  # noqa: BLE001 — status must not fail on extras
        pass
    return {
        "nodes_alive": sum(1 for n in nodes if n.get("alive", True)),
        "nodes_dead": sum(1 for n in nodes if not n.get("alive", True)),
        # head fault-tolerance posture (durable log / reconciliation)
        "head_ha": head_ha,
        "resources_total": total,
        "resources_available": avail,
        "actors": {
            "ALIVE": sum(1 for a in actors if a["state"] == "ALIVE"),
            "DEAD": sum(1 for a in actors if a["state"] == "DEAD"),
            "other": sum(
                1 for a in actors if a["state"] not in ("ALIVE", "DEAD")
            ),
        },
        "workers": sum(len(st.get("workers", {})) for st in agents),
        # demand no launchable node type can ever satisfy (autoscaler
        # shape-aware scheduler; reference autoscaler/v2 reports the same
        # through `ray status`'s "infeasible requests" section)
        "infeasible_demand": infeasible,
        "object_store": {
            "used_bytes": sum(st["store_usage"][0] for st in agents),
            "capacity_bytes": sum(st["store_usage"][1] for st in agents),
            "spilled_objects": sum(
                st.get("spill_stats", {}).get("spilled_objects", 0)
                for st in agents
            ),
            "spilled_bytes": sum(
                st.get("spill_stats", {}).get("spilled_bytes", 0)
                for st in agents
            ),
        },
    }


def _worker_addresses(
    address: Optional[str],
    agents: Optional[List[Dict[str, Any]]] = None,
) -> List[str]:
    if agents is None:
        agents = _agent_states(address)
    addrs = []
    for st in agents:
        for w in st.get("workers", {}).values():
            addrs.append(w["address"])
    # drivers execute nothing but OWN events (submit/dispatch lifecycle
    # instants) and metrics: reach them through the job registry so
    # out-of-process consumers (rt summary, a standalone dashboard) see
    # owner-side data, not just executor slices
    try:
        for job in list_jobs(address):
            if job.get("alive") and job.get("driver_address"):
                addrs.append(job["driver_address"])
    except (RpcError, RuntimeError):
        pass
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is not None:
        addrs.append(w.address)
    # dedup (an in-process driver is also a live job) preserving order
    return list(dict.fromkeys(addrs))


def _collect_task_events(
    address: Optional[str],
    types: Optional[List[str]] = None,
) -> Tuple[List[Dict[str, Any]], int]:
    """Gather every worker's event ring. Returns (events, dropped_total)
    — dropped counts ring evictions, so a truncated timeline is
    detectable instead of silently missing its head. ``types`` filters
    worker-side (rpc_get_task_events), so periodic consumers (the
    metrics-history sampler) don't ship full rings every tick."""
    events: List[Dict[str, Any]] = []
    dropped = 0
    for addr in _worker_addresses(address):
        try:
            reply = _pool.get(addr).call(
                "get_task_events", types=types, timeout_s=10.0
            )
        except RpcConnectionError:
            _pool.drop(addr)
            continue
        except RpcError:
            continue
        if isinstance(reply, dict):
            events.extend(reply.get("events", ()))
            dropped += int(reply.get("dropped", 0))
        else:  # legacy list shape
            events.extend(reply)
    return events, dropped


def task_events(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Collect task execution + lifecycle events from every live worker."""
    return _collect_task_events(address)[0]


def timeline(address: Optional[str] = None,
             out_path: Optional[str] = None) -> Any:
    """Chrome-trace (chrome://tracing / perfetto) of task executions
    (parity: `ray timeline`, reference scripts.py:2171).

    Execution events render as "X" duration slices. Lifecycle events
    (observability/tracing.py) add cross-process causality: each task
    with a "submitted" instant on its owner and an execution slice on a
    worker emits a flow arrow (``ph:"s"`` on the owner pid →
    ``ph:"f"`` binding to the execution slice on the executor pid), plus
    an owner-side "submit:" slice spanning submit → dispatch so the
    arrow has a visible anchor."""
    events = task_events(address)
    trace: List[Dict[str, Any]] = []
    exec_slices: Dict[str, Dict[str, Any]] = {}
    submits: Dict[str, Dict[str, Any]] = {}
    dispatches: Dict[str, Dict[str, Any]] = {}
    request_spans: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        etype = e.get("type")
        if etype == "request":
            # serve request leg: one slice per component, joined below
            # into a cross-pid flow by trace id
            args = {"trace_id": e["trace_id"]}
            for k in ("queue_us", "status", "model", "cached", "ttft_us",
                      "tokens", "kv_bytes"):
                if k in e:
                    args[k] = e[k]
            trace.append({
                "name": f"{e['component']}:{e.get('deployment', '')}",
                "cat": "request",
                "ph": "X",
                "ts": e["ts_us"],
                "dur": max(int(e.get("dur_us", 0)), 1),
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": args,
            })
            request_spans.setdefault(e["trace_id"], []).append(e)
            continue
        if etype == "pipeline":
            trace.append({
                "name": f"stage{e['stage']}:{e['kind']}",
                "cat": "pipeline",
                "ph": "X",
                "ts": e["ts_us"],
                "dur": max(int(e.get("dur_us", 0)), 1),
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": {k: e[k] for k in
                         ("step", "microbatch", "bubble_frac", "schedule")
                         if k in e},
            })
            continue
        if etype == "collective":
            trace.append({
                "name": f"collective:{e['op']}",
                "cat": "collective",
                "ph": "X",
                "ts": e["ts_us"],
                "dur": max(int(e.get("dur_us", 0)), 1),
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": {"nbytes": e.get("nbytes", 0)},
            })
            continue
        if etype == "alert":
            # alert transitions render as global instants so a FIRING
            # marker lines up against the request spans that caused it
            trace.append({
                "name": f"alert:{e.get('rule', '?')}:{e.get('state', '?')}",
                "cat": "alert",
                "ph": "i",
                "s": "g",
                "ts": e["ts_us"],
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": {
                    k: e[k]
                    for k in ("rule", "state", "metric", "severity", "value")
                    if e.get(k) is not None
                },
            })
            continue
        if etype == "autoscale":
            # serve autoscaler decisions: global instants so a scale-up
            # marker lines up against the TTFT spans that triggered it
            trace.append({
                "name": (
                    f"autoscale:{e.get('deployment', '?')}:"
                    f"{e.get('direction', '?')}"
                ),
                "cat": "autoscale",
                "ph": "i",
                "s": "g",
                "ts": e["ts_us"],
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": {
                    k: e[k]
                    for k in ("deployment", "from", "to", "direction",
                              "reason")
                    if e.get(k) is not None
                },
            })
            continue
        if etype == "stall":
            # stall watchdog marker: a process-scoped instant carrying
            # the stuck thread's stack, joinable by task_id
            trace.append({
                "name": f"stall:{e.get('name', '?')}",
                "cat": "stall",
                "ph": "i",
                "s": "p",
                "ts": e["ts_us"],
                "pid": e.get("worker") or e.get("pid", 0),
                "tid": e.get("pid", 0),
                "args": {
                    k: e[k]
                    for k in ("task_id", "name", "elapsed_s", "stack")
                    if k in e
                },
            })
            continue
        if etype == "lifecycle":
            if e["phase"] == "submitted":
                submits[e["task_id"]] = e
            elif e["phase"] == "dispatched":
                dispatches[e["task_id"]] = e
            elif e["phase"] == "lease_granted":
                # lease churn as thread-scoped instants: correlates pool
                # growth with the queue spikes that caused it
                trace.append({
                    "name": f"lease_granted:{e.get('target', '')}",
                    "cat": "lease",
                    "ph": "i",
                    "s": "t",
                    "ts": e["ts_us"],
                    "pid": e["worker"],
                    "tid": e.get("pid", 0),
                    "args": {"lease_id": e["task_id"]},
                })
            continue
        slice_ev = {
            "name": e["name"],
            "cat": "actor_task" if e.get("actor_id") else "task",
            "ph": "X",
            "ts": e["ts_us"],
            "dur": e["dur_us"],
            "pid": e["worker"],
            "tid": e.get("pid", 0),
            "args": {"task_id": e["task_id"]},
        }
        trace.append(slice_ev)
        exec_slices[e["task_id"]] = e
    for task_id, sub in submits.items():
        exec_e = exec_slices.get(task_id)
        disp = dispatches.get(task_id)
        # owner-side anchor slice: submit -> dispatch (or a 1us tick)
        anchor_end = disp["ts_us"] if disp else sub["ts_us"] + 1
        trace.append({
            "name": f"submit:{sub['name']}",
            "cat": "task_submit",
            "ph": "X",
            "ts": sub["ts_us"],
            "dur": max(anchor_end - sub["ts_us"], 1),
            "pid": sub["worker"],
            "tid": sub.get("pid", 0),
            "args": {"task_id": task_id},
        })
        if exec_e is None:
            continue
        flow = {
            "name": sub["name"],
            "cat": "task_flow",
            "id": task_id,
        }
        trace.append({
            **flow, "ph": "s", "ts": sub["ts_us"],
            "pid": sub["worker"], "tid": sub.get("pid", 0),
        })
        trace.append({
            # bp:"e" binds the flow end to the ENCLOSING slice — the
            # execution "X" beginning at the same ts on this pid/tid
            **flow, "ph": "f", "bp": "e", "ts": exec_e["ts_us"],
            "pid": exec_e["worker"], "tid": exec_e.get("pid", 0),
        })
    # request flow: one arrow chain per trace id, hop by hop through the
    # components in time order (proxy → router → replica → engine),
    # binding each step to the enclosing component slice
    for trace_id, spans in request_spans.items():
        if len(spans) < 2:
            continue
        spans = sorted(spans, key=lambda s: s["ts_us"])
        flow = {"name": "request", "cat": "request_flow", "id": trace_id}
        for i, s in enumerate(spans):
            ph = "s" if i == 0 else ("f" if i == len(spans) - 1 else "t")
            step = {
                **flow, "ph": ph, "ts": s["ts_us"],
                "pid": s.get("worker") or s.get("pid", 0),
                "tid": s.get("pid", 0),
            }
            if ph == "f":
                step["bp"] = "e"
            trace.append(step)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(trace, f)
        return out_path
    return trace


def _percentiles(values: List[float]) -> Dict[str, float]:
    vs = sorted(values)
    n = len(vs)

    def pick(q: float) -> float:
        return vs[min(n - 1, int(q * n))]

    return {
        "p50": pick(0.50), "p95": pick(0.95), "p99": pick(0.99),
        "mean": sum(vs) / n, "max": vs[-1],
    }


def _latency_entry(splits: Dict[str, List[float]],
                   count_key: str) -> Dict[str, Any]:
    """Shared rollup for task_summary/request_summary: one count (taken
    from count_key's split — the one every sample contributes to) plus
    p50/p95/p99/mean/max for each non-empty split."""
    entry: Dict[str, Any] = {"count": len(splits.get(count_key, ()))}
    for key, vals in splits.items():
        if vals:
            entry[key] = _percentiles(vals)
    return entry


def task_summary(address: Optional[str] = None) -> Dict[str, Any]:
    """Per-task-name latency summary joined across processes: queue wait
    (owner "submitted" instant → executor slice start) and execution
    time, each as p50/p95/p99/mean/max seconds. The "where does time go
    between submit and run" view (reference `ray summary tasks`)."""
    events, dropped = _collect_task_events(address)
    submits: Dict[str, int] = {}
    for e in events:
        if e.get("type") == "lifecycle" and e["phase"] == "submitted":
            submits[e["task_id"]] = e["ts_us"]
    per_name: Dict[str, Dict[str, List[float]]] = {}
    for e in events:
        if e.get("type") is not None:
            continue  # lifecycle/request/pipeline/collective events
        rec = per_name.setdefault(
            e["name"], {"queue_wait_s": [], "exec_s": []}
        )
        rec["exec_s"].append(e["dur_us"] / 1e6)
        sub_ts = submits.get(e["task_id"])
        if sub_ts is not None:
            # clamp: submit/exec stamps come from different processes'
            # wall clocks; sub-ms skew must not produce negative waits
            rec["queue_wait_s"].append(max(e["ts_us"] - sub_ts, 0) / 1e6)
    tasks = {}
    for name, rec in sorted(per_name.items()):
        tasks[name] = _latency_entry(rec, "exec_s")
    return {"tasks": tasks, "events_dropped": dropped}


def request_summary(address: Optional[str] = None) -> Dict[str, Any]:
    """Per-deployment serve-request latency summary from the request
    spans stamped along the proxy → router → replica → engine path:
    end-to-end (proxy span), queue (router span: pick + wait for a
    replica assignment), and execution (replica span), each as
    p50/p95/p99/mean/max seconds. Engine spans additionally split
    time-to-first-token by prefix-cache outcome (ttft_cached_s vs
    ttft_cold_s), and disaggregated deployments contribute prefill_s /
    transfer_s legs, so a hot-vs-cold or remote-prefill regression is
    visible without raw span spelunking."""
    events, dropped = _collect_task_events(address, types=["request"])
    per_dep: Dict[str, Dict[str, List[float]]] = {}
    for e in events:
        if e.get("type") != "request":
            continue
        rec = per_dep.setdefault(e.get("deployment") or "?", {
            "e2e_s": [], "queue_s": [], "exec_s": [],
        })
        dur_s = e.get("dur_us", 0) / 1e6
        comp = e.get("component")
        if comp == "proxy":
            rec["e2e_s"].append(dur_s)
        elif comp == "router":
            rec["queue_s"].append(dur_s)
        elif comp == "replica":
            rec["exec_s"].append(dur_s)
        elif comp == "engine":
            ttft_us = e.get("ttft_us")
            if ttft_us:
                key = "ttft_cached_s" if e.get("cached") else "ttft_cold_s"
                rec.setdefault(key, []).append(ttft_us / 1e6)
        elif comp == "prefill":
            rec.setdefault("prefill_s", []).append(dur_s)
        elif comp == "transfer":
            rec.setdefault("transfer_s", []).append(dur_s)
    deployments = {}
    for dep, rec in sorted(per_dep.items()):
        deployments[dep] = _latency_entry(rec, "e2e_s")
    return {"deployments": deployments, "events_dropped": dropped}


def tasks(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Task-level state listing (parity: `ray list tasks`) built from the
    workers' task-event rings: one record per task_id with the inferred
    state — QUEUED (submitted, not dispatched), RUNNING (dispatched, no
    execution slice yet), FINISHED (execution slice recorded). Bounded by
    the rings: evicted history is absent, so this is a window, not an
    archive."""
    events, _dropped = _collect_task_events(address)
    recs: Dict[str, Dict[str, Any]] = {}

    def rec(task_id: str) -> Dict[str, Any]:
        return recs.setdefault(task_id, {
            "task_id": task_id, "name": None, "state": "UNKNOWN",
            "owner": None, "worker": None, "actor_id": None,
            "submitted_ts_us": None, "dispatched_ts_us": None,
            "start_ts_us": None, "dur_us": None,
        })

    for e in events:
        etype = e.get("type")
        if etype not in (None, "lifecycle"):
            continue  # request/pipeline/collective spans carry no task_id
        if etype == "lifecycle":
            if e["phase"] == "lease_granted":
                continue  # lease churn, not a task transition
            r = rec(e["task_id"])
            r["name"] = r["name"] or e.get("name")
            if e["phase"] == "submitted":
                r["submitted_ts_us"] = e["ts_us"]
                r["owner"] = e.get("worker")
            elif e["phase"] == "dispatched":
                r["dispatched_ts_us"] = e["ts_us"]
        else:
            r = rec(e["task_id"])
            r["name"] = e["name"]
            r["worker"] = e.get("worker")
            r["actor_id"] = e.get("actor_id")
            r["start_ts_us"] = e["ts_us"]
            r["dur_us"] = e["dur_us"]
    for r in recs.values():
        if r["dur_us"] is not None:
            r["state"] = "FINISHED"
        elif r["dispatched_ts_us"] is not None:
            r["state"] = "RUNNING"
        elif r["submitted_ts_us"] is not None:
            r["state"] = "QUEUED"
    return sorted(
        recs.values(),
        key=lambda r: r["submitted_ts_us"] or r["start_ts_us"] or 0,
    )


def objects(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Object-level state listing (parity: `ray list objects` /
    `ray memory`): every node's shm/spill store inventory, annotated with
    owner-side reference state (remote borrows + in-flight pins) so a
    leaked borrow shows up as an old pinned object. Owner-only objects
    (small values in a memory store) appear with location "owner" when
    they hold borrows."""
    out: List[Dict[str, Any]] = []
    for n in list_nodes(address):
        if not n.get("alive", True):
            continue
        try:
            reply = _pool.get(n["address"]).call("list_objects", timeout_s=10.0)
        except RpcConnectionError:
            _pool.drop(n["address"])
            continue
        except RpcError:
            continue
        for o in reply["objects"]:
            out.append({**o, "node_id": reply["node_id"], "location": "store",
                        "borrows": 0, "inflight_pins": 0, "owner": None})
    # borrow/pin state is OBJECT-scoped (it lives at the owner): annotate
    # every replica row of the id, not an arbitrary one — an object may
    # sit in several nodes' stores at once
    by_id: Dict[str, List[Dict[str, Any]]] = {}
    for r in out:
        by_id.setdefault(r["object_id"], []).append(r)
    for addr in _worker_addresses(address):
        try:
            stats = _pool.get(addr).call("borrow_stats", timeout_s=10.0)
        except RpcConnectionError:
            _pool.drop(addr)
            continue
        except (RpcError, RuntimeError):
            continue
        pins = stats.get("inflight_pins", {})
        borrows = stats.get("borrows", {})
        for oid in set(borrows) | set(pins):
            recs = by_id.get(oid)
            if recs is None:
                rec = {
                    "object_id": oid, "node_id": None, "location": "owner",
                    "size": None, "sealed": None, "state": "memory",
                    "borrows": 0, "inflight_pins": 0, "owner": None,
                }
                by_id[oid] = [rec]
                out.append(rec)
                recs = [rec]
            for rec in recs:
                rec["owner"] = stats.get("address", addr)
                rec["borrows"] += int(borrows.get(oid, 0))
                pin = pins.get(oid)
                if pin:
                    rec["inflight_pins"] += int(pin["count"])
                    rec["oldest_pin_age_s"] = max(
                        rec.get("oldest_pin_age_s", 0.0),
                        pin["oldest_age_s"],
                    )
    return out


def worker_logs(address: Optional[str] = None,
                tail_bytes: int = 4096) -> List[Dict[str, Any]]:
    """Tails of every worker's captured stdout/stderr across the cluster
    (`rt logs`): the minimal path from a `print()` inside a task to the
    driver machine."""
    logs: List[Dict[str, Any]] = []
    for n in list_nodes(address):
        if not n.get("alive", True):
            continue
        try:
            logs.extend(_pool.get(n["address"]).call(
                "tail_worker_logs", tail_bytes=tail_bytes, timeout_s=10.0
            ))
        except RpcConnectionError:
            _pool.drop(n["address"])
        except RpcError:
            pass
    return logs


def _copy_metric(m: Dict) -> Dict:
    """Deep-enough copy of one metric snapshot: the merge mutates series
    state in place, and the caller's input must survive unchanged."""
    series = {}
    for k, v in m["series"].items():
        series[k] = (
            dict(v, buckets=list(v["buckets"])) if isinstance(v, dict) else v
        )
    return dict(m, series=series)


def _merge_snapshot_into(merged: Dict[str, Dict], snap: Dict[str, Dict]) -> None:
    """Merge one process's metric snapshot into the aggregate: counters
    and histograms sum, gauges keep the latest per series."""
    for name, m in snap.items():
        cur = merged.get(name)
        if cur is None:
            # copy on adoption: later snapshots merge INTO this entry,
            # and mutating the first process's reply in place would
            # corrupt the caller's data (and double-count on re-merge)
            merged[name] = _copy_metric(m)
            continue
        for k, v in m["series"].items():
            if m["kind"] == "counter":
                cur["series"][k] = cur["series"].get(k, 0.0) + v
            elif m["kind"] == "gauge":
                cur["series"][k] = v
            else:  # histogram
                if tuple(m.get("boundaries", ())) != tuple(
                    cur.get("boundaries", ())
                ):
                    # divergent boundaries across workers: bucket-wise
                    # merge would be meaningless and render a corrupt
                    # Prometheus histogram (le="+Inf" < _count). Keep
                    # count/sum, drop bucket detail for the metric.
                    cur["boundaries"] = ()
                    for st in cur["series"].values():
                        st["buckets"] = []
                prev = cur["series"].get(k)
                if prev is None:
                    cur["series"][k] = (
                        v if cur.get("boundaries")
                        else dict(v, buckets=[])
                    )
                else:
                    prev["sum"] += v["sum"]
                    prev["count"] += v["count"]
                    prev["buckets"] = [
                        a + b
                        for a, b in zip(prev["buckets"], v["buckets"])
                    ]


def merge_metric_snapshots(
    snapshots: Iterable[Dict[str, Dict]],
) -> Dict[str, Dict]:
    """Pure aggregation over per-process snapshot_all() dicts (exposed
    for direct testing of the merge semantics)."""
    merged: Dict[str, Dict] = {}
    for snap in snapshots:
        _merge_snapshot_into(merged, snap)
    return merged


def metrics_history(
    name: Optional[str] = None,
    tags: Optional[Dict[str, str]] = None,
    window_s: Optional[float] = None,
    step_s: Optional[float] = None,
    address: Optional[str] = None,
) -> Dict[str, Any]:
    """Query the head's retained metric time series
    (observability/history.py). ``name=None`` returns the store
    inventory + sampler stats ({"enabled": False} when the sampler is
    off). With a name: aggregated ring points — gauges as
    ``{"ts","value"}``, counters as reset-aware ``{"ts","delta","rate"}``,
    histograms as per-window bucket deltas — at the finest resolution
    tier covering ``window_s`` (or the tier matching ``step_s``)."""
    return _with_control(address, lambda c: c.call(
        "metrics_history", name=name, tags=tags, window_s=window_s,
        step_s=step_s, timeout_s=10.0,
    ))


def alerts(address: Optional[str] = None) -> Dict[str, Any]:
    """Current alert-rule states from the head's alert engine
    (observability/alerts.py): one entry per rule with its definition,
    state (ok/pending/firing), last evaluated value, and how long it has
    been in that state."""
    return _with_control(
        address, lambda c: c.call("alerts", timeout_s=10.0)
    )


def autoscale_status(address: Optional[str] = None) -> Dict[str, Any]:
    """Serve control-loop snapshot the controller publishes to the head
    KV each reconcile tick (serve/controller.py _publish_status): per
    deployment the replica targets, running/draining counts with
    per-drainer progress, the last autoscale decision and the signals
    behind it. Returns {} when no controller is publishing (or the
    snapshot is stale — controller gone > 60s)."""
    try:
        raw = _control(address).call(
            "kv_get", ns="serve", key="autoscale_status", timeout_s=5.0
        )
    except Exception:  # noqa: BLE001 — no head / no serve: empty
        return {}
    if not raw:
        return {}
    try:
        rec = json.loads(bytes(raw).decode())
    except (ValueError, UnicodeDecodeError):
        return {}
    if time.time() - rec.get("ts", 0) > 60.0:  # controller gone: stale
        return {}
    return rec.get("deployments", {})


def _fleet_addresses(
    address: Optional[str],
    node: Optional[str] = None,
) -> List[str]:
    """Every profile/stack-dump target: control store + node agents +
    workers (+ live drivers). A ``node`` id prefix narrows to that
    node's agent and workers."""
    agents = _agent_states(address)
    if node:
        agents = [
            st for st in agents if st["node_id"].startswith(node)
        ]
        addrs = [st["address"] for st in agents]
        for st in agents:
            addrs.extend(
                w["address"] for w in st.get("workers", {}).values()
            )
    else:
        addrs = []
        try:
            addrs.append(_control(address).address)
        except RuntimeError:
            pass
        addrs.extend(st["address"] for st in agents)
        addrs.extend(_worker_addresses(address, agents=agents))
    return list(dict.fromkeys(addrs))


def profile(
    duration_s: float = 5.0,
    hz: float = 99.0,
    address: Optional[str] = None,
) -> Dict[str, Any]:
    """Fleet-wide sampling profile (`rt profile`): fan ``rpc_profile``
    to the control store, every node agent and every worker
    concurrently, then merge the folded stacks. Replies carry a
    per-process token, so the single-node case (head + agent + driver
    in one process) counts each process once. The merged dict has
    ``folded`` (stack -> samples), ``subsystems`` (subsystem ->
    samples) and sampling totals."""
    from concurrent.futures import ThreadPoolExecutor

    from ray_tpu.observability import profiler as profiler_mod

    addrs = _fleet_addresses(address)

    def one(addr: str):
        try:
            return _pool.get(addr).call(
                "profile", duration_s=duration_s, hz=hz,
                timeout_s=float(duration_s) + 30.0,
            )
        except RpcConnectionError:
            _pool.drop(addr)
            return None
        except RpcError:
            return None

    with ThreadPoolExecutor(
        max_workers=min(max(len(addrs), 1), 32),
        thread_name_prefix="profile-fan",
    ) as fan:
        replies = list(fan.map(one, addrs))
    merged = profiler_mod.merge(replies)
    merged["targets"] = len(addrs)
    merged["replies"] = sum(1 for r in replies if r)
    merged["duration_s"] = float(duration_s)
    merged["hz"] = float(hz)
    return merged


def stacks(
    address: Optional[str] = None,
    node: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """All-thread stack dumps from every live process in the fleet
    (`rt stacks`), deduped by process token; ``node`` (node-id prefix)
    narrows to one node's agent + workers."""
    dumps: List[Dict[str, Any]] = []
    seen: set = set()
    for addr in _fleet_addresses(address, node=node):
        try:
            dump = _pool.get(addr).call("stack_dump", timeout_s=10.0)
        except RpcConnectionError:
            _pool.drop(addr)
            continue
        except RpcError:
            continue
        token = dump.get("token") if isinstance(dump, dict) else None
        if token and token in seen:
            continue
        if token:
            seen.add(token)
        dump["address"] = addr
        dumps.append(dump)
    return dumps


def crash_reports(
    address: Optional[str] = None,
    pid: Optional[int] = None,
    node: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Crash artifacts (black boxes + faulthandler crash files) from
    every node's session crash dir (`rt postmortem`), dead processes
    included — that is the point."""
    out: List[Dict[str, Any]] = []
    for n in list_nodes(address):
        if not n.get("alive", True):
            continue
        if node and not n["node_id"].startswith(node):
            continue
        try:
            reply = _pool.get(n["address"]).call(
                "crash_reports", pid=pid, timeout_s=10.0
            )
        except RpcConnectionError:
            _pool.drop(n["address"])
            continue
        except RpcError:
            continue
        for rec in reply.get("reports", []):
            out.append({**rec, "node_id": reply.get("node_id")})
    return out


def cluster_metrics(address: Optional[str] = None) -> Dict[str, Dict]:
    """Aggregate metrics (utils/metrics.py) across the whole cluster —
    every worker, every node agent, and the control store — so the
    built-in core metrics (scheduler/lease/object-store series that live
    in daemon processes) surface alongside user metrics. Replies carry a
    per-process token: on the head, control store + agent + driver share
    ONE process and must be counted once, not three times."""
    addrs: List[str] = [a for a in [address] if a is not None]
    if not addrs:
        try:
            addrs.append(_control(None).address)
        except RuntimeError:
            pass
    agents = _agent_states(address)
    addrs.extend(st["address"] for st in agents)
    addrs.extend(_worker_addresses(address, agents=agents))
    merged: Dict[str, Dict] = {}
    seen_tokens = set()
    for addr in addrs:
        try:
            reply = _pool.get(addr).call("get_metrics", timeout_s=10.0)
        except RpcConnectionError:
            _pool.drop(addr)
            continue
        except RpcError:
            continue
        if isinstance(reply, dict) and "metrics" in reply and "token" in reply:
            token, snap = reply["token"], reply["metrics"]
            if token in seen_tokens:
                continue
            seen_tokens.add(token)
        else:  # legacy shape: a bare snapshot, no process identity
            snap = reply
        _merge_snapshot_into(merged, snap)
    return merged
