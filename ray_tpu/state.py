"""Cluster state API.

Parity: ray.util.state (reference python/ray/util/state/api.py) + the
`ray timeline` exporter (scripts.py:2171): list nodes/actors/jobs/
placement groups/workers/tasks, aggregate metrics, and dump a
Chrome-trace timeline of task execution events collected from every
worker's event buffer.

Functions accept an explicit control-store address, or use the connected
runtime's when omitted.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ray_tpu.utils.rpc import ClientPool, RpcConnectionError, RpcError

# Pooled connections: the dashboard's 5s auto-refresh page renders several
# state calls per view — dialing and closing a fresh socket per call would
# hammer the control store.
_pool = ClientPool("state-api")


def _control(address: Optional[str]):
    if address is None:
        from ray_tpu.core import worker as worker_mod

        w = worker_mod.global_worker_or_none()
        if w is None:
            raise RuntimeError(
                "not connected: pass address= or call ray_tpu.init() first"
            )
        address = w.control_address
    return _pool.get(address)


def _with_control(address, fn):
    return fn(_control(address))


def list_nodes(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(
        address, lambda c: c.call("get_nodes", alive_only=False)
    )


def list_actors(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_actors"))


def list_jobs(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_jobs"))


def list_placement_groups(address: Optional[str] = None) -> List[Dict[str, Any]]:
    return _with_control(address, lambda c: c.call("list_placement_groups"))


def _agent_states(address: Optional[str]) -> List[Dict[str, Any]]:
    nodes = [n for n in list_nodes(address) if n.get("alive", True)]
    out = []
    for n in nodes:
        try:
            out.append(
                _pool.get(n["address"]).call("get_state", timeout_s=10.0)
            )
        except RpcConnectionError:
            _pool.drop(n["address"])  # dead connection: rebuild next time
        except RpcError:
            pass  # slow, not dead: dropping would break concurrent users
    return out


def list_workers(address: Optional[str] = None) -> List[Dict[str, Any]]:
    out = []
    for st in _agent_states(address):
        for wid, w in st.get("workers", {}).items():
            out.append({"worker_id": wid, "node_id": st["node_id"], **w})
    return out


def cluster_status(address: Optional[str] = None) -> Dict[str, Any]:
    """`rt status` summary: nodes, resources, stores, actors, jobs."""
    nodes = list_nodes(address)
    agents = _agent_states(address)
    actors = list_actors(address)
    infeasible = None
    try:
        raw = _control(address).call(
            "kv_get", ns="autoscaler", key="infeasible", timeout_s=5.0
        )
        if raw:
            rec = json.loads(bytes(raw).decode())
            if time.time() - rec.get("ts", 0) < 60.0:  # recent only
                infeasible = rec
    except Exception:  # noqa: BLE001 — status must not fail on extras
        pass
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for st in agents:
        for k, v in st["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in st["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v
    return {
        "nodes_alive": sum(1 for n in nodes if n.get("alive", True)),
        "nodes_dead": sum(1 for n in nodes if not n.get("alive", True)),
        "resources_total": total,
        "resources_available": avail,
        "actors": {
            "ALIVE": sum(1 for a in actors if a["state"] == "ALIVE"),
            "DEAD": sum(1 for a in actors if a["state"] == "DEAD"),
            "other": sum(
                1 for a in actors if a["state"] not in ("ALIVE", "DEAD")
            ),
        },
        "workers": sum(len(st.get("workers", {})) for st in agents),
        # demand no launchable node type can ever satisfy (autoscaler
        # shape-aware scheduler; reference autoscaler/v2 reports the same
        # through `ray status`'s "infeasible requests" section)
        "infeasible_demand": infeasible,
        "object_store": {
            "used_bytes": sum(st["store_usage"][0] for st in agents),
            "capacity_bytes": sum(st["store_usage"][1] for st in agents),
            "spilled_objects": sum(
                st.get("spill_stats", {}).get("spilled_objects", 0)
                for st in agents
            ),
            "spilled_bytes": sum(
                st.get("spill_stats", {}).get("spilled_bytes", 0)
                for st in agents
            ),
        },
    }


def _worker_addresses(address: Optional[str]) -> List[str]:
    addrs = []
    for st in _agent_states(address):
        for w in st.get("workers", {}).values():
            addrs.append(w["address"])
    from ray_tpu.core import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is not None:
        addrs.append(w.address)  # the driver executes nothing but owns events
    return addrs


def task_events(address: Optional[str] = None) -> List[Dict[str, Any]]:
    """Collect task execution events from every live worker."""
    events: List[Dict[str, Any]] = []
    for addr in _worker_addresses(address):
        try:
            events.extend(
                _pool.get(addr).call("get_task_events", timeout_s=10.0)
            )
        except RpcConnectionError:
            _pool.drop(addr)
        except RpcError:
            pass
    return events


def timeline(address: Optional[str] = None,
             out_path: Optional[str] = None) -> Any:
    """Chrome-trace (chrome://tracing / perfetto) of task executions
    (parity: `ray timeline`, reference scripts.py:2171)."""
    events = task_events(address)
    trace = [
        {
            "name": e["name"],
            "cat": "actor_task" if e.get("actor_id") else "task",
            "ph": "X",
            "ts": e["ts_us"],
            "dur": e["dur_us"],
            "pid": e["worker"],
            "tid": e.get("pid", 0),
            "args": {"task_id": e["task_id"]},
        }
        for e in events
    ]
    if out_path:
        with open(out_path, "w") as f:
            json.dump(trace, f)
        return out_path
    return trace


def cluster_metrics(address: Optional[str] = None) -> Dict[str, Dict]:
    """Aggregate user metrics (utils/metrics.py) across all workers:
    counters/histograms sum, gauges keep the latest per series."""
    merged: Dict[str, Dict] = {}
    for addr in _worker_addresses(address):
        try:
            snap = _pool.get(addr).call("get_metrics", timeout_s=10.0)
        except RpcConnectionError:
            _pool.drop(addr)
            continue
        except RpcError:
            continue
        for name, m in snap.items():
            cur = merged.get(name)
            if cur is None:
                merged[name] = m
                continue
            for k, v in m["series"].items():
                if m["kind"] == "counter":
                    cur["series"][k] = cur["series"].get(k, 0.0) + v
                elif m["kind"] == "gauge":
                    cur["series"][k] = v
                else:  # histogram
                    if tuple(m.get("boundaries", ())) != tuple(
                        cur.get("boundaries", ())
                    ):
                        # divergent boundaries across workers: bucket-wise
                        # merge would be meaningless and render a corrupt
                        # Prometheus histogram (le="+Inf" < _count). Keep
                        # count/sum, drop bucket detail for the metric.
                        cur["boundaries"] = ()
                        for st in cur["series"].values():
                            st["buckets"] = []
                    prev = cur["series"].get(k)
                    if prev is None:
                        cur["series"][k] = (
                            v if cur.get("boundaries")
                            else dict(v, buckets=[])
                        )
                    else:
                        prev["sum"] += v["sum"]
                        prev["count"] += v["count"]
                        prev["buckets"] = [
                            a + b
                            for a, b in zip(prev["buckets"], v["buckets"])
                        ]
    return merged
