"""GPT-2 — the flagship model (BASELINE.md configs 1/3: GPT-2 pretrain).

Pure-JAX pytree implementation, TPU-first:
  - layers STACKED on a leading L dim and iterated with lax.scan → one
    compiled block body instead of L unrolled copies (fast compile, XLA
    pipelines the loop);
  - fused QKV projection, single (D, 3, H, Dh) matmul feeding the MXU;
  - vocab padded to a multiple of 128 (MXU lane width);
  - bf16 compute / fp32 master params; logits + softmax in fp32;
  - jax.checkpoint (remat) around each block to trade FLOPs for HBM;
  - GSPMD sharding via parallel.sharding.gpt_rules: TP on heads/hidden,
    FSDP on the complementary dim, batch over dp axes, sequence over cp.

The weights are compatible in spirit (same architecture: pre-LN, learned
positions, GELU, tied LM head) with the reference's GPT-2 configs used by
its Train benchmarks (reference release/train_tests/benchmark).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from ray_tpu.ops.attention import attention as attention_op


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    d_model: int = 768
    n_layer: int = 12
    n_head: int = 12
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # "full" recomputes the whole block; "dots" saves matmul outputs and
    # recomputes only cheap elementwise ops (less recompute, more HBM)
    remat_policy: str = "full"
    attn_impl: str = "reference"  # reference | flash | ring
    cp_axis: Optional[str] = None  # mesh axis name when attn_impl="ring"
    # Cross-entropy in T-chunks of this many tokens: the [B,T,V] fp32
    # logits tensor (6.6GB for gpt2-small at B=32,T=1024) never
    # materializes — each chunk's logits are recomputed in the backward
    # pass. 0 disables chunking.
    loss_chunk: int = 128
    # lax.scan unroll factor over layers. 1 = rolled (fast compile, the
    # right default for deep models); n_layer = fully unrolled (XLA sees
    # every layer: no dynamic-update-slice gradient stacking, better
    # inter-layer scheduling — measurably faster for small L).
    scan_unroll: int = 1
    # "chunked": scan+checkpoint CE (loss_chunk controls chunk size).
    # "fused": custom-vjp CE that emits bf16 dlogits in the forward —
    # backward is matmul-only, no [B,T,V] fp32 tensor ever exists.
    loss_impl: str = "chunked"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def padded_vocab(self) -> int:
        return ((self.vocab_size + 127) // 128) * 128

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    def num_params(self) -> int:
        d, l, v = self.d_model, self.n_layer, self.padded_vocab
        per_layer = 4 * d * d + 2 * 4 * d * d + 3 * d + 4 * d + 2 * 2 * d + d
        return v * d + self.n_positions * d + l * per_layer + 2 * d


# Reference configs (model sizes the reference benchmarks use)
CONFIGS = {
    "gpt2-small": GPT2Config(),
    "gpt2-medium": GPT2Config(d_model=1024, n_layer=24, n_head=16),
    "gpt2-large": GPT2Config(d_model=1280, n_layer=36, n_head=20),
    "gpt2-xl": GPT2Config(d_model=1600, n_layer=48, n_head=25),
    "gpt2-tiny": GPT2Config(  # tests / dryruns
        vocab_size=256, n_positions=128, d_model=64, n_layer=2, n_head=4,
        remat=False,
    ),
}


def init(rng: jax.Array, cfg: GPT2Config) -> Dict[str, Any]:
    """Initialize the parameter pytree (stacked-layer layout)."""
    d, l, h, hd, f = cfg.d_model, cfg.n_layer, cfg.n_head, cfg.head_dim, cfg.d_ff
    v, t = cfg.padded_vocab, cfg.n_positions
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    proj_std = std / math.sqrt(2 * l)  # GPT-2 residual-scale init
    pd = cfg.param_dtype

    def norm(key, shape, s):
        return (jax.random.normal(key, shape) * s).astype(pd)

    return {
        "wte": norm(next(k), (v, d), std),
        "wpe": norm(next(k), (t, d), std),
        "blocks": {
            "ln1": {"scale": jnp.ones((l, d), pd), "bias": jnp.zeros((l, d), pd)},
            "ln2": {"scale": jnp.ones((l, d), pd), "bias": jnp.zeros((l, d), pd)},
            "attn": {
                "qkv": {
                    "kernel": norm(next(k), (l, d, 3, h, hd), std),
                    "bias": jnp.zeros((l, 3, h, hd), pd),
                },
                "proj": {
                    "kernel": norm(next(k), (l, h, hd, d), proj_std),
                    "bias": jnp.zeros((l, d), pd),
                },
            },
            "mlp": {
                "fc_in": {
                    "kernel": norm(next(k), (l, d, f), std),
                    "bias": jnp.zeros((l, f), pd),
                },
                "fc_out": {
                    "kernel": norm(next(k), (l, f, d), proj_std),
                    "bias": jnp.zeros((l, d), pd),
                },
            },
        },
        "ln_f": {"scale": jnp.ones((d,), pd), "bias": jnp.zeros((d,), pd)},
    }


def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _block(x, layer, cfg: GPT2Config):
    """One pre-LN transformer block (body of the layer scan)."""
    dt = cfg.dtype
    h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = (
        jnp.einsum("btd,dchn->btchn", h, layer["attn"]["qkv"]["kernel"].astype(dt))
        + layer["attn"]["qkv"]["bias"].astype(dt)
    )
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H,Dh]
    att = attention_op(
        q, k, v, causal=True, impl=cfg.attn_impl, axis_name=cfg.cp_axis
    )
    # checkpointable under the "dots+attn" remat policy: saving the
    # attention output avoids re-running the flash kernel in the backward
    att = jax.ad_checkpoint.checkpoint_name(att, "attn_out")
    att = (
        jnp.einsum("bthn,hnd->btd", att, layer["attn"]["proj"]["kernel"].astype(dt))
        + layer["attn"]["proj"]["bias"].astype(dt)
    )
    x = x + att
    h = _layernorm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
    h = (
        jnp.einsum("btd,df->btf", h, layer["mlp"]["fc_in"]["kernel"].astype(dt))
        + layer["mlp"]["fc_in"]["bias"].astype(dt)
    )
    h = jax.nn.gelu(h, approximate=True)
    h = (
        jnp.einsum("btf,fd->btd", h, layer["mlp"]["fc_out"]["kernel"].astype(dt))
        + layer["mlp"]["fc_out"]["bias"].astype(dt)
    )
    return x + h


def backbone(params: Dict[str, Any], tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> final hidden states [B, T, D] (compute dtype)."""
    B, T = tokens.shape
    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:T][None]

    def body(carry, layer):
        return _block(carry, layer, cfg), None

    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "dots_saveable":
            # Save every matmul output and the attention output; recompute
            # only cheap elementwise ops (LN, gelu, bias) in the backward.
            # ~6GB of residuals at gpt2-small B=32,T=1024 — the right
            # trade on a 16GB chip, vs "full" re-running every block fwd.
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_saveable,
                jax.checkpoint_policies.save_only_these_names("attn_out"),
            )
        elif cfg.remat_policy == "attn_out":
            # Save ONLY the attention output (50MB/layer): the backward's
            # recompute re-runs the cheap matmuls but never the flash
            # kernel — the most expensive-to-recompute op in the block.
            policy = jax.checkpoint_policies.save_only_these_names("attn_out")
        body = jax.checkpoint(body, prevent_cse=False, policy=policy)
    x, _ = jax.lax.scan(
        body, x, params["blocks"], unroll=min(cfg.scan_unroll, cfg.n_layer)
    )
    return _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, padded_vocab] (fp32)."""
    x = backbone(params, tokens, cfg)
    dt = cfg.dtype
    # tied LM head: bf16 operands on the MXU, fp32 accumulation → fp32
    # logits for a stable softmax without paying the 8x fp32-matmul tax
    return jnp.einsum(
        "btd,vd->btv", x.astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )


def _chunk_nll(x_chunk, targets_chunk, wte, cfg: GPT2Config) -> jax.Array:
    """Cross-entropy over one T-chunk; returns summed NLL (fp32 scalar)."""
    logits = jnp.einsum(
        "bcd,vd->bcv", x_chunk, wte,
        preferred_element_type=jnp.float32,
    )
    if cfg.padded_vocab != cfg.vocab_size:
        pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
        logits = jnp.where(pad_mask[None, None], -1e30, logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets_chunk[..., None], axis=-1)[..., 0]
    return nll.sum()


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ce(x, wte, targets, n_chunks: int, vocab_size: int):
    loss, _ = _fused_ce_fwd(x, wte, targets, n_chunks, vocab_size)
    return loss


def _fused_ce_fwd(x, wte, targets, n_chunks: int, vocab_size: int):
    """Chunked CE that emits dlogits = (softmax - onehot) in bf16 DURING
    the forward: the [B,T,V] fp32 logits tensor never materializes, the
    backward is two pure matmuls (dx = dl @ wte, dwte = dl^T @ x) with no
    recompute, and the softmax elementwise work runs once over fp32
    chunks instead of three passes over a 6.6GB tensor.

    lax.map (sequential) over chunks rather than vmap: it GUARANTEES one
    fp32 logits chunk live at a time (vmap leaves that to XLA fusion
    luck) and measured 22ms/step FASTER on v5e (PROFILE.md)."""
    B, T, D = x.shape
    V = wte.shape[0]
    C = T // n_chunks
    xs = jnp.moveaxis(x.reshape(B, n_chunks, C, D), 1, 0)
    ts = jnp.moveaxis(targets.reshape(B, n_chunks, C), 1, 0)

    def chunk(xt):
        xc, tc = xt
        logits = jnp.einsum("bcd,vd->bcv", xc, wte,
                            preferred_element_type=jnp.float32)
        if vocab_size != V:
            pad = jax.lax.broadcasted_iota(jnp.int32, (1, 1, V), 2) >= vocab_size
            logits = jnp.where(pad, -1e30, logits)
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m)
        s = jnp.sum(e, axis=-1, keepdims=True)
        lse = (m + jnp.log(s))[..., 0]                       # [B, C]
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll = jnp.sum(lse - tgt)
        p = e / s
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (1, 1, V), 2) == tc[..., None]
        )
        dl = (p - onehot.astype(p.dtype)).astype(x.dtype)    # [B, C, V] bf16
        return nll, dl

    nlls, dls = jax.lax.map(chunk, (xs, ts))
    loss = jnp.sum(nlls) / (B * T)
    return loss, (x, wte, dls)


def _fused_ce_bwd(n_chunks: int, vocab_size: int, res, g):
    x, wte, dls = res
    B, T, D = x.shape
    C = T // n_chunks
    scale = g / (B * T)
    xs = jnp.moveaxis(x.reshape(B, n_chunks, C, D), 1, 0)
    # dx = dl @ wte ; dwte = sum_chunks dl^T @ x
    dx = jnp.einsum("nbcv,vd->nbcd", dls, wte)               # bf16 matmul
    dx = jnp.moveaxis(dx, 0, 1).reshape(B, T, D) * scale.astype(x.dtype)
    dwte = jnp.einsum("nbcv,nbcd->vd", dls, xs,
                      preferred_element_type=jnp.float32) * scale
    dtargets = None
    return dx.astype(x.dtype), dwte, dtargets


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def loss_fn(params, tokens, cfg: GPT2Config) -> jax.Array:
    """Next-token cross-entropy; masks padded-vocab logits.

    With cfg.loss_chunk > 0 the head runs per T-chunk under jax.checkpoint:
    peak memory holds one [B, C, V] logits block instead of [B, T, V], and
    the backward pass recomputes each chunk's logits instead of re-reading
    a giant fp32 tensor from HBM (bandwidth ≫ the recompute FLOPs here).
    """
    x = backbone(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    B, T, D = x.shape
    dt = cfg.dtype
    wte = params["wte"].astype(dt)
    if cfg.loss_impl == "fused":
        n_chunks = max(1, T // max(1, cfg.loss_chunk)) if cfg.loss_chunk else 1
        while T % n_chunks:
            n_chunks -= 1
        return _fused_ce(x, wte, targets, n_chunks, cfg.vocab_size)
    C = cfg.loss_chunk
    if C <= 0 or T <= C:
        total = _chunk_nll(x, targets, wte, cfg)
        return total / (B * T)

    # T rarely divides C (next-token loss makes T = seq-1, e.g. 1023):
    # scan over the full chunks, then one remainder chunk outside the
    # scan, so chunking never silently degrades to the [B,T,V] fallback.
    nC, rem = divmod(T, C)
    xs = jnp.moveaxis(x[:, : nC * C].reshape(B, nC, C, D), 1, 0)    # [nC, B, C, D]
    ts = jnp.moveaxis(targets[:, : nC * C].reshape(B, nC, C), 1, 0)  # [nC, B, C]

    def chunk_body(acc, xt):
        xc, tc = xt
        return acc + _chunk_nll(xc, tc, wte, cfg), None

    total, _ = jax.lax.scan(
        jax.checkpoint(chunk_body, prevent_cse=False), jnp.float32(0.0), (xs, ts)
    )
    if rem:
        total = total + jax.checkpoint(
            lambda xc, tc: _chunk_nll(xc, tc, wte, cfg), prevent_cse=False
        )(x[:, nC * C :], targets[:, nC * C :])
    return total / (B * T)


def make_train_step(cfg: GPT2Config, optimizer):
    """Returns train_step(params, opt_state, tokens) -> (params, opt_state, loss).

    Pure function of pytrees: jit it with shardings from
    parallel.sharding.gpt_rules over any mesh (dp/fsdp/tp/cp) — XLA
    inserts the gradient psum over data axes from the shardings alone.
    """

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: (p + u).astype(p.dtype), params, updates)
        return params, opt_state, loss

    return train_step
