"""Model zoo: pure-JAX pytree models with GSPMD sharding annotations."""
