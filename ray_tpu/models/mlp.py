"""MLP classifier — BASELINE.md config 1 (Fashion-MNIST MLP, the
reference's PR1 Train example, python/ray/train test fixtures)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Sequence[int] = (128, 128)
    num_classes: int = 10
    dtype: Any = jnp.float32


def init(rng: jax.Array, cfg: MLPConfig) -> Dict[str, Any]:
    dims = [cfg.in_dim, *cfg.hidden, cfg.num_classes]
    keys = jax.random.split(rng, len(dims) - 1)
    return {
        f"layer{i}": {
            "kernel": (
                jax.random.normal(keys[i], (dims[i], dims[i + 1]))
                * (2.0 / dims[i]) ** 0.5
            ).astype(cfg.dtype),
            "bias": jnp.zeros((dims[i + 1],), cfg.dtype),
        }
        for i in range(len(dims) - 1)
    }


def forward(params: Dict[str, Any], x: jax.Array) -> jax.Array:
    n = len(params)
    for i in range(n):
        layer = params[f"layer{i}"]
        x = x @ layer["kernel"] + layer["bias"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def loss_fn(params, batch) -> jax.Array:
    x, y = batch
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
    return nll.mean()


def accuracy(params, batch) -> jax.Array:
    x, y = batch
    return (forward(params, x).argmax(-1) == y).mean()
