"""KV-cached autoregressive decoding for GPT-2 — the serving engine core.

Parity role: the engine tier the reference delegates to vLLM
(/root/reference/python/ray/llm/_internal/serve/engines/vllm/) — here a
native JAX engine: a prefill/decode split over a slot-based static-shape
KV cache, so generating token N costs one single-token forward over
cached K/V instead of re-running the whole prefix (the round-3 engine
recomputed O(N·T·model) per generation).

TPU-first shape discipline: the cache is ``[L, S, T_max, H, Dh]`` with a
fixed slot count S — every jitted function has static shapes, admission
of a new request into a free slot is a ``dynamic_update_slice`` row
write, and the decode step runs all S slots batched whether or not each
is active (masked), which is exactly the static-batch regime the MXU
wants. Continuous batching lives OUTSIDE jit (the engine loop admits
requests between steps; serve/llm.py drives it).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import gpt2
from ray_tpu.models.gpt2 import GPT2Config, _layernorm


def init_cache(cfg: GPT2Config, slots: int, t_max: int):
    """(k, v) caches: [n_layer, S, T_max, H, Dh] in the compute dtype."""
    shape = (cfg.n_layer, slots, t_max, cfg.n_head, cfg.head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def _qkv(h, layer, cfg: GPT2Config):
    dt = cfg.dtype
    qkv = (
        jnp.einsum("btd,dchn->btchn", h, layer["attn"]["qkv"]["kernel"].astype(dt))
        + layer["attn"]["qkv"]["bias"].astype(dt)
    )
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H,Dh]


def _proj_mlp(x, att, layer, cfg: GPT2Config):
    dt = cfg.dtype
    att = (
        jnp.einsum("bthn,hnd->btd", att, layer["attn"]["proj"]["kernel"].astype(dt))
        + layer["attn"]["proj"]["bias"].astype(dt)
    )
    x = x + att
    h = _layernorm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
    h = (
        jnp.einsum("btd,df->btf", h, layer["mlp"]["fc_in"]["kernel"].astype(dt))
        + layer["mlp"]["fc_in"]["bias"].astype(dt)
    )
    h = jax.nn.gelu(h, approximate=True)
    h = (
        jnp.einsum("btf,fd->btd", h, layer["mlp"]["fc_out"]["kernel"].astype(dt))
        + layer["mlp"]["fc_out"]["bias"].astype(dt)
    )
    return x + h


@partial(jax.jit, static_argnums=(0,), donate_argnums=(4, 5))
def prefill(cfg: GPT2Config, params, tokens, length, cache_k, cache_v,
            slot):
    """Run the full prompt ([1, P] right-padded) through the model,
    writing each layer's K/V into cache row ``slot``; return the last
    real position's logits [vocab] and the updated caches.

    fori_loop (not scan) over layers so the cache updates are IN-PLACE
    dynamic_update_slices on the donated carry — a scan would stack
    fresh [L, S, T, H, Dh] cache outputs, copying the whole cache per
    call (measured 300x slower at gpt2-small)."""
    dt = cfg.dtype
    P = tokens.shape[1]
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:P][None]
    causal = jnp.tril(jnp.ones((P, P), bool))

    def body(layer_idx, carry):
        x, ck, cv = carry
        layer = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, layer_idx, axis=0, keepdims=False
            ),
            params["blocks"],
        )
        h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _qkv(h, layer, cfg)
        # causal self-attention over the prompt itself
        scale = 1.0 / (cfg.head_dim ** 0.5)
        scores = jnp.einsum("bthn,bshn->bhts", q, k) * scale
        scores = jnp.where(causal[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        att = jnp.einsum("bhts,bshn->bthn", probs, v)
        x = _proj_mlp(x, att, layer, cfg)
        # park this layer's prompt K/V in the slot's cache row (in place)
        ck = jax.lax.dynamic_update_slice(
            ck, k.astype(dt)[None], (layer_idx, slot, 0, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cv, v.astype(dt)[None], (layer_idx, slot, 0, 0, 0)
        )
        return x, ck, cv

    x, cache_k, cache_v = jax.lax.fori_loop(
        0, cfg.n_layer, body, (x, cache_k, cache_v)
    )
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = jax.lax.dynamic_index_in_dim(
        x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False
    )
    logits = jnp.einsum(
        "d,vd->v", last.astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[: cfg.vocab_size], cache_k, cache_v


@partial(jax.jit, donate_argnums=(2, 3))
def write_prefix(prefix_k, prefix_v, cache_k, cache_v, slot):
    """Copy precomputed prefix K/V ``[L, C, H, Dh]`` into cache row
    ``slot`` (positions 0..C-1) — the admission path for a prefix-cache
    hit or a disaggregated KV import: the slot starts life already
    holding C tokens of context without running a single prefill flop.

    C must be one of a small set of sizes (block multiples from the
    prefix pool, pow-2 padded lengths from kv_transfer) so the jit
    bucket count stays bounded like prefill's P buckets."""
    ck = jax.lax.dynamic_update_slice(
        cache_k, prefix_k.astype(cache_k.dtype)[:, None], (0, slot, 0, 0, 0)
    )
    cv = jax.lax.dynamic_update_slice(
        cache_v, prefix_v.astype(cache_v.dtype)[:, None], (0, slot, 0, 0, 0)
    )
    return ck, cv


@partial(jax.jit, static_argnums=(0,), donate_argnums=(5, 6))
def prefill_extend(cfg: GPT2Config, params, tokens, start, length, cache_k,
                   cache_v, slot):
    """Prefill ONLY the uncached tail of a prompt: ``tokens`` [1, P]
    (right-padded, ``length`` real) are positions start..start+P-1, and
    cache row ``slot`` already holds K/V for positions 0..start-1
    (written by :func:`write_prefix`). Writes the tail's K/V at offset
    ``start``, attends the tail over prefix+tail, and returns the last
    real tail position's logits [vocab] plus the updated caches.

    The caller guarantees start + P <= T_max (dynamic_update_slice would
    silently clamp the write offset otherwise)."""
    dt = cfg.dtype
    P = tokens.shape[1]
    T = cache_k.shape[2]
    pos = start + jnp.arange(P)
    x = (
        params["wte"].astype(dt)[tokens]
        + params["wpe"].astype(dt)[jnp.clip(pos, 0, T - 1)][None]
    )
    # tail position start+i may attend every cached position 0..start+i
    mask = jnp.arange(T)[None] <= pos[:, None]  # [P, T]

    def body(layer_idx, carry):
        x, ck, cv = carry
        layer = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, layer_idx, axis=0, keepdims=False
            ),
            params["blocks"],
        )
        h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _qkv(h, layer, cfg)  # [1, P, H, Dh]
        # park the tail's K/V after the prefix (in place on the donated
        # carry), then attend over the whole row so the tail sees the
        # cached prefix it never recomputed
        ck = jax.lax.dynamic_update_slice(
            ck, k.astype(dt)[None], (layer_idx, slot, start, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cv, v.astype(dt)[None], (layer_idx, slot, start, 0, 0)
        )
        ck_l = jax.lax.dynamic_slice(
            ck, (layer_idx, slot, 0, 0, 0),
            (1, 1, T, cfg.n_head, cfg.head_dim),
        )[:, 0]  # [1, T, H, Dh]
        cv_l = jax.lax.dynamic_slice(
            cv, (layer_idx, slot, 0, 0, 0),
            (1, 1, T, cfg.n_head, cfg.head_dim),
        )[:, 0]
        scale = 1.0 / (cfg.head_dim ** 0.5)
        scores = jnp.einsum("bthn,bshn->bhts", q, ck_l) * scale
        scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        att = jnp.einsum("bhts,bshn->bthn", probs, cv_l)
        x = _proj_mlp(x, att, layer, cfg)
        return x, ck, cv

    x, cache_k, cache_v = jax.lax.fori_loop(
        0, cfg.n_layer, body, (x, cache_k, cache_v)
    )
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = jax.lax.dynamic_index_in_dim(
        x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False
    )
    logits = jnp.einsum(
        "d,vd->v", last.astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[: cfg.vocab_size], cache_k, cache_v


def _decode_step_impl(cfg: GPT2Config, params, last_tokens, lengths, cache_k,
                      cache_v):
    """One token for every slot: [S] last tokens at positions ``lengths``
    attend over their cached prefixes. Returns logits [S, vocab] and the
    updated caches (new K/V scattered at position ``lengths``)."""
    dt = cfg.dtype
    S = last_tokens.shape[0]
    T = cache_k.shape[2]
    pos = jnp.clip(lengths, 0, T - 1)
    x = (
        params["wte"].astype(dt)[last_tokens][:, None]
        + params["wpe"].astype(dt)[pos][:, None]
    )  # [S, 1, D]
    rows = jnp.arange(S)
    mask = jnp.arange(T)[None] <= pos[:, None]  # attend 0..pos

    def body(layer_idx, carry):
        x, ck, cv = carry
        layer = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, layer_idx, axis=0, keepdims=False
            ),
            params["blocks"],
        )
        h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _qkv(h, layer, cfg)  # [S, 1, H, Dh]
        # in-place scatter of the new token's K/V rows on the donated carry
        ck = ck.at[layer_idx, rows, pos].set(k[:, 0].astype(dt))
        cv = cv.at[layer_idx, rows, pos].set(v[:, 0].astype(dt))
        ck_l = jax.lax.dynamic_index_in_dim(
            ck, layer_idx, axis=0, keepdims=False
        )  # [S, T, H, Dh]
        cv_l = jax.lax.dynamic_index_in_dim(
            cv, layer_idx, axis=0, keepdims=False
        )
        scale = 1.0 / (cfg.head_dim ** 0.5)
        scores = jnp.einsum("shn,sthn->sht", q[:, 0], ck_l) * scale
        scores = jnp.where(mask[:, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        att = jnp.einsum("sht,sthn->shn", probs, cv_l)[:, None]
        x = _proj_mlp(x, att, layer, cfg)
        return x, ck, cv

    x, cache_k, cache_v = jax.lax.fori_loop(
        0, cfg.n_layer, body, (x, cache_k, cache_v)
    )
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum(
        "sd,vd->sv", x[:, 0].astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[:, : cfg.vocab_size], cache_k, cache_v


decode_step = partial(jax.jit, static_argnums=(0,), donate_argnums=(4, 5))(
    _decode_step_impl
)


def sample(logits, temps, greedy_mask, rng):
    """Per-row temperature/greedy sampling. logits [S, V]."""
    greedy = jnp.argmax(logits, axis=-1)
    sampled = jax.random.categorical(
        rng, logits / jnp.maximum(temps, 1e-6)[:, None]
    )
    return jnp.where(greedy_mask, greedy, sampled).astype(jnp.int32)


@partial(jax.jit, donate_argnums=(1, 2, 3))
def update_rows(last_tokens, lengths, temps, greedy_mask, rows, row_last,
                row_len, row_temps, row_greedy):
    """Incremental decode-state update: write admission/retirement
    values into ``rows`` of the device-resident step state WITHOUT
    re-uploading the full arrays — the async decode pipeline's
    steady-state churn path (one small scatter per array instead of
    five host->device transfers at every admit/retire).

    ``last_tokens`` is deliberately NOT donated: in the single-step
    decode regime it aliases the chunk's token output, which the host
    may not have materialized yet (the in-flight lookahead)."""
    return (
        last_tokens.at[rows].set(row_last),
        lengths.at[rows].set(row_len),
        temps.at[rows].set(row_temps),
        greedy_mask.at[rows].set(row_greedy),
    )


@partial(jax.jit, donate_argnums=(1, 2, 3, 4))
def update_rows_paged(last_tokens, lengths, temps, greedy_mask,
                      page_tables, rows, row_last, row_len, row_temps,
                      row_greedy, row_tables):
    """Paged twin of :func:`update_rows`: also rewrites the changed
    sequences' page-table rows (a retired row's table goes all-zero so
    its junk scatters land in the scratch page; an admitted row brings
    its freshly reserved table). Same donation caveat on
    ``last_tokens``."""
    return (
        last_tokens.at[rows].set(row_last),
        lengths.at[rows].set(row_len),
        temps.at[rows].set(row_temps),
        greedy_mask.at[rows].set(row_greedy),
        page_tables.at[rows].set(row_tables),
    )


@partial(jax.jit, static_argnums=(0,), donate_argnums=(4, 5))
def decode_and_sample(cfg: GPT2Config, params, last_tokens, lengths,
                      cache_k, cache_v, temps, greedy_mask, rng_base, step):
    """decode_step + sample (+ RNG fold + cursor bump) fused into ONE
    dispatch — on a remote/tunneled chip the per-call round trip dominates
    single-token decode, so the serving loop pays exactly one dispatch +
    one token sync per step. Returns (next_tokens, next_lengths, k, v):
    the engine feeds them straight back in without re-uploading."""
    logits, cache_k, cache_v = _decode_step_impl(
        cfg, params, last_tokens, lengths, cache_k, cache_v
    )
    rng = jax.random.fold_in(rng_base, step)
    nxt = sample(logits, temps, greedy_mask, rng)
    return nxt, lengths + 1, cache_k, cache_v


# -- paged KV cache (one pool for generation + prefix pages) -----------
#
# vLLM-style paged attention at the jnp level: physical KV pages
# ``[L, N_pages, B, H, Dh]`` in HBM, per-sequence page tables
# ``[S, MaxPages]`` mapping virtual position p to physical row
# (table[p // B], p % B). A prefix-cache hit points the table at pages
# another sequence already wrote (zero copies); admission reserves
# ceil(tokens/B) pages up front so tables never change mid-flight.
# Page 0 is reserved scratch: inactive rows carry all-zero tables and
# length 0, so their junk scatters land there and the jitted step needs
# no validity branch (same masked-static-batch regime as the slot
# kernels above).


def init_paged_cache(cfg: GPT2Config, num_pages: int, page_tokens: int):
    """(k, v) page pools: [n_layer, N_pages, B, H, Dh], compute dtype."""
    shape = (cfg.n_layer, num_pages, page_tokens, cfg.n_head, cfg.head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


@partial(jax.jit, donate_argnums=(2, 3))
def write_pages(k_blocks, v_blocks, cache_k, cache_v, pages):
    """Batched page import (disaggregated KV shipment): write
    ``k_blocks``/``v_blocks`` [L, n, B, H, Dh] into physical pages
    ``pages`` [n] of the pool. The ONLY block-copy path left in the
    paged engine — prefix hits bump refcounts instead."""
    ck = cache_k.at[:, pages].set(k_blocks.astype(cache_k.dtype))
    cv = cache_v.at[:, pages].set(v_blocks.astype(cache_v.dtype))
    return ck, cv


@partial(jax.jit, static_argnums=(0,), donate_argnums=(5, 6))
def prefill_paged(cfg: GPT2Config, params, tokens, start, length, cache_k,
                  cache_v, page_table):
    """Prefill one CHUNK of a prompt into paged KV: ``tokens`` [1, P]
    (right-padded, ``length`` real) are virtual positions
    start..start+P-1 of the sequence whose page table is ``page_table``
    [MaxPages]; pages holding positions 0..start-1 are already written
    (a prefix hit, a KV import, or this sequence's previous chunk —
    chunked prefill is just repeated calls with advancing ``start``).
    Scatters the chunk's K/V through the page table, attends the chunk
    over the whole gathered row, and returns the last real position's
    logits [vocab] plus the updated pools.

    The caller guarantees start + P <= MaxPages * B (bucket the chunk
    width against that cap); positions past the sequence's reserved
    pages hit table entries that are 0 = the scratch page, so padding
    scatters are harmless exactly like prefill_extend's padded tail."""
    dt = cfg.dtype
    P = tokens.shape[1]
    B = cache_k.shape[2]
    max_pages = page_table.shape[0]
    T = max_pages * B  # virtual row width
    W = params["wpe"].shape[0]
    pos = start + jnp.arange(P)
    x = (
        params["wte"].astype(dt)[tokens]
        + params["wpe"].astype(dt)[jnp.clip(pos, 0, W - 1)][None]
    )
    # chunk position start+i may attend every written position 0..start+i
    mask = jnp.arange(T)[None] <= pos[:, None]  # [P, T]
    page_of = page_table[jnp.clip(pos // B, 0, max_pages - 1)]  # [P]
    off = pos % B

    def body(layer_idx, carry):
        x, ck, cv = carry
        layer = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, layer_idx, axis=0, keepdims=False
            ),
            params["blocks"],
        )
        h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _qkv(h, layer, cfg)  # [1, P, H, Dh]
        # scatter the chunk's K/V through the page table (in place on
        # the donated carry), then gather the whole virtual row so the
        # chunk sees prefix pages it never computed
        ck = ck.at[layer_idx, page_of, off].set(k[0].astype(dt))
        cv = cv.at[layer_idx, page_of, off].set(v[0].astype(dt))
        ck_l = jax.lax.dynamic_index_in_dim(
            ck, layer_idx, axis=0, keepdims=False
        )[page_table].reshape(T, cfg.n_head, cfg.head_dim)[None]
        cv_l = jax.lax.dynamic_index_in_dim(
            cv, layer_idx, axis=0, keepdims=False
        )[page_table].reshape(T, cfg.n_head, cfg.head_dim)[None]
        scale = 1.0 / (cfg.head_dim ** 0.5)
        scores = jnp.einsum("bthn,bshn->bhts", q, ck_l) * scale
        scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        att = jnp.einsum("bhts,bshn->bthn", probs, cv_l)
        x = _proj_mlp(x, att, layer, cfg)
        return x, ck, cv

    x, cache_k, cache_v = jax.lax.fori_loop(
        0, cfg.n_layer, body, (x, cache_k, cache_v)
    )
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    last = jax.lax.dynamic_index_in_dim(
        x[0], jnp.maximum(length - 1, 0), axis=0, keepdims=False
    )
    logits = jnp.einsum(
        "d,vd->v", last.astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[: cfg.vocab_size], cache_k, cache_v


def _decode_paged_impl(cfg: GPT2Config, params, last_tokens, lengths,
                       cache_k, cache_v, page_tables):
    """One token for every sequence over paged KV: [S] last tokens at
    virtual positions ``lengths`` scatter their new K/V through
    ``page_tables`` [S, MaxPages] and attend over their gathered rows.
    Returns logits [S, vocab] and the updated pools."""
    dt = cfg.dtype
    S = last_tokens.shape[0]
    B = cache_k.shape[2]
    max_pages = page_tables.shape[1]
    T = max_pages * B
    W = params["wpe"].shape[0]
    pos = jnp.clip(lengths, 0, T - 1)
    x = (
        params["wte"].astype(dt)[last_tokens][:, None]
        + params["wpe"].astype(dt)[jnp.clip(pos, 0, W - 1)][:, None]
    )  # [S, 1, D]
    rows = jnp.arange(S)
    mask = jnp.arange(T)[None] <= pos[:, None]  # attend 0..pos
    page_of = page_tables[rows, pos // B]  # [S]
    off = pos % B

    def body(layer_idx, carry):
        x, ck, cv = carry
        layer = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, layer_idx, axis=0, keepdims=False
            ),
            params["blocks"],
        )
        h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _qkv(h, layer, cfg)  # [S, 1, H, Dh]
        # in-place scatter of the new token's K/V through the tables
        # (inactive rows have zero tables: their junk lands in the
        # scratch page)
        ck = ck.at[layer_idx, page_of, off].set(k[:, 0].astype(dt))
        cv = cv.at[layer_idx, page_of, off].set(v[:, 0].astype(dt))
        ck_l = jax.lax.dynamic_index_in_dim(
            ck, layer_idx, axis=0, keepdims=False
        )[page_tables].reshape(S, T, cfg.n_head, cfg.head_dim)
        cv_l = jax.lax.dynamic_index_in_dim(
            cv, layer_idx, axis=0, keepdims=False
        )[page_tables].reshape(S, T, cfg.n_head, cfg.head_dim)
        scale = 1.0 / (cfg.head_dim ** 0.5)
        scores = jnp.einsum("shn,sthn->sht", q[:, 0], ck_l) * scale
        scores = jnp.where(mask[:, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        att = jnp.einsum("sht,sthn->shn", probs, cv_l)[:, None]
        x = _proj_mlp(x, att, layer, cfg)
        return x, ck, cv

    x, cache_k, cache_v = jax.lax.fori_loop(
        0, cfg.n_layer, body, (x, cache_k, cache_v)
    )
    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = jnp.einsum(
        "sd,vd->sv", x[:, 0].astype(dt), params["wte"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits[:, : cfg.vocab_size], cache_k, cache_v


@partial(jax.jit, static_argnums=(0,), donate_argnums=(4, 5))
def decode_paged_and_sample(cfg: GPT2Config, params, last_tokens, lengths,
                            cache_k, cache_v, page_tables, temps,
                            greedy_mask, rng_base, step):
    """Paged twin of :func:`decode_and_sample`: decode + sample (+ RNG
    fold + cursor bump) fused into ONE dispatch."""
    logits, cache_k, cache_v = _decode_paged_impl(
        cfg, params, last_tokens, lengths, cache_k, cache_v, page_tables
    )
    rng = jax.random.fold_in(rng_base, step)
    nxt = sample(logits, temps, greedy_mask, rng)
    return nxt, lengths + 1, cache_k, cache_v


@partial(jax.jit, static_argnums=(0, 10), donate_argnums=(4, 5))
def decode_multi_paged(cfg: GPT2Config, params, last_tokens, lengths,
                       cache_k, cache_v, page_tables, temps, greedy_mask,
                       rng_base, n_steps: int, step0):
    """Paged twin of :func:`decode_multi`: ``n_steps`` tokens per
    sequence in ONE dispatch, page-table scatter recomputed per step
    on device (the tables themselves are fixed — admission reserved
    every page up front)."""
    S = last_tokens.shape[0]
    toks0 = jnp.zeros((n_steps, S), jnp.int32)

    def body(i, carry):
        last, lens, ck, cv, toks = carry
        logits, ck, cv = _decode_paged_impl(
            cfg, params, last, lens, ck, cv, page_tables
        )
        rng = jax.random.fold_in(rng_base, step0 + i)
        nxt = sample(logits, temps, greedy_mask, rng)
        toks = jax.lax.dynamic_update_index_in_dim(toks, nxt, i, axis=0)
        return nxt, lens + 1, ck, cv, toks

    last, lens, cache_k, cache_v, toks = jax.lax.fori_loop(
        0, n_steps, body, (last_tokens, lengths, cache_k, cache_v, toks0)
    )
    return toks, last, lens, cache_k, cache_v


@partial(jax.jit, static_argnums=(0, 9), donate_argnums=(4, 5))
def decode_multi(cfg: GPT2Config, params, last_tokens, lengths, cache_k,
                 cache_v, temps, greedy_mask, rng_base, n_steps: int,
                 step0):
    """Generate ``n_steps`` tokens per slot in ONE dispatch (fori_loop on
    device). On a remote/tunneled chip each dispatch costs a full network
    round trip, so chunking K tokens per call multiplies serving
    throughput by ~K; the engine picks K from the active slots' remaining
    budgets and drops to K=1 whenever requests are waiting for admission
    (continuous batching latency stays one step)."""
    S = last_tokens.shape[0]
    toks0 = jnp.zeros((n_steps, S), jnp.int32)

    def body(i, carry):
        last, lens, ck, cv, toks = carry
        logits, ck, cv = _decode_step_impl(cfg, params, last, lens, ck, cv)
        rng = jax.random.fold_in(rng_base, step0 + i)
        nxt = sample(logits, temps, greedy_mask, rng)
        toks = jax.lax.dynamic_update_index_in_dim(toks, nxt, i, axis=0)
        return nxt, lens + 1, ck, cv, toks

    last, lens, cache_k, cache_v, toks = jax.lax.fori_loop(
        0, n_steps, body, (last_tokens, lengths, cache_k, cache_v, toks0)
    )
    return toks, last, lens, cache_k, cache_v
