"""rt — the cluster CLI.

Parity: the `ray` CLI's observability commands (reference
python/ray/scripts/scripts.py — status, list, timeline :2171). Run as
`python -m ray_tpu.cli <cmd>` (or `python -m ray_tpu <cmd>`); point it
at a cluster with --address or RT_ADDRESS.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _fmt_table(rows: List[dict], columns: List[str]) -> str:
    if not rows:
        return "(none)"
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in columns
    }
    head = "  ".join(c.upper().ljust(widths[c]) for c in columns)
    lines = [head, "-" * len(head)]
    for r in rows:
        lines.append(
            "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns)
        )
    return "\n".join(lines)


# Single shared interpolation (utils/metrics.py): the renderer, state
# rollups, history store, and alert engine must all agree on quantile
# math.
from ray_tpu.utils.metrics import hist_quantile as _hist_quantile  # noqa: E402

_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _sparkline(vals: List[float], width: int = 12) -> str:
    """Render the trailing ``width`` values as a unicode sparkline."""
    vals = [v for v in vals if v is not None][-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK_GLYPHS[min(
            int((v - lo) / span * (len(_SPARK_GLYPHS) - 1) + 0.5),
            len(_SPARK_GLYPHS) - 1,
        )]
        for v in vals
    )


def _router_deps(mx: dict) -> List[str]:
    m = mx.get("rt_serve_router_requests_total") or {}
    deps = set()
    for k in m.get("series", {}):
        deps.add(dict(zip(m.get("tag_keys", ()), k)).get("deployment") or "?")
    return sorted(deps)


def _top_history(state_mod, addr, since: float, deps: List[str]):
    """History-derived `rt top` view: per-deployment TTFT percentiles
    over the trailing ``since`` window plus a QPS sparkline, from the
    head's metrics-history store. None when the sampler is disabled."""
    try:
        root = state_mod.metrics_history(address=addr)
    except Exception:  # noqa: BLE001 — older head / no handler
        return None
    if not isinstance(root, dict) or not root.get("enabled"):
        return None
    out = {"window_s": since, "deployments": {}}
    for dep in deps:
        entry: dict = {}
        try:
            h = state_mod.metrics_history(
                "rt_serve_ttft_s", tags={"deployment": dep},
                window_s=since, address=addr,
            )
            pts = [p for p in h.get("points", ()) if p.get("buckets")]
            if pts and h.get("boundaries"):
                buckets = [0.0] * max(len(p["buckets"]) for p in pts)
                for p in pts:
                    for i, b in enumerate(p["buckets"]):
                        buckets[i] += b
                entry["ttft_p50_s"] = _hist_quantile(
                    h["boundaries"], buckets, 0.5
                )
                entry["ttft_p95_s"] = _hist_quantile(
                    h["boundaries"], buckets, 0.95
                )
            q = state_mod.metrics_history(
                "rt_serve_router_requests_total", tags={"deployment": dep},
                window_s=since, address=addr,
            )
            rates = [p.get("rate", 0.0) for p in q.get("points", ())]
            if rates:
                entry["qps_points"] = rates
                entry["qps_avg"] = sum(rates) / len(rates)
        except Exception:  # noqa: BLE001 — a hiccup must not kill a frame
            pass
        if entry:
            out["deployments"][dep] = entry
    return out


def _render_top(mx: dict, reqs: dict, qps: Optional[dict],
                alerts_rep: Optional[dict] = None,
                hist: Optional[dict] = None,
                ascale: Optional[dict] = None) -> str:
    """One `rt top` frame from a state.cluster_metrics() aggregate and a
    state.request_summary() rollup. ``qps`` maps deployment -> req/s
    computed by the caller from successive router-counter frames (None
    on the first frame / --once). ``alerts_rep`` / ``hist`` (state.alerts
    and the metrics-history view) add the FIRING banner and the windowed
    sparkline/percentile columns when the head-side sampler is on.
    ``ascale`` (state.autoscale_status) adds the control-loop columns:
    replicas as running/target(+Nd draining), shed counts, and the last
    autoscale decision with its reason."""

    def metric(name: str) -> dict:
        return mx.get(name) or {"series": {}, "tag_keys": ()}

    def tags(m: dict, key) -> dict:
        return dict(zip(m.get("tag_keys", ()), key))

    def scalar_sum(name: str) -> float:
        return sum(metric(name)["series"].values())

    def by_tag(name: str, tag: str) -> dict:
        """Sum a counter/gauge's series per value of one tag."""
        m = metric(name)
        out: dict = {}
        for k, v in m["series"].items():
            t = tags(m, k).get(tag) or "?"
            out[t] = out.get(t, 0.0) + v
        return out

    def hist_by_tag(name: str, tag: str) -> dict:
        """Per-tag merged (bounds, buckets, count, sum) for a histogram."""
        m = metric(name)
        bounds = m.get("boundaries", ())
        out: dict = {}
        for k, v in m["series"].items():
            t = tags(m, k).get(tag) or "?"
            cur = out.setdefault(
                t, {"bounds": bounds, "buckets": [0] * len(v["buckets"]),
                    "count": 0, "sum": 0.0},
            )
            cur["count"] += v["count"]
            cur["sum"] += v["sum"]
            cur["buckets"] = [
                a + b for a, b in zip(cur["buckets"], v["buckets"])
            ] or list(v["buckets"])
        return out

    def ms(v: Optional[float]) -> str:
        return f"{v * 1e3:.1f}" if v is not None else "-"

    out = []
    firing = [
        a for a in (alerts_rep or {}).get("alerts", ())
        if a.get("state") == "firing"
    ]
    if firing:
        out.append("!! FIRING: " + ", ".join(
            f"{a['name']}"
            + (f" ({a['value']:.3g})" if a.get("value") is not None else "")
            for a in firing
        ))
        out.append("")
    out.append(
        f"sched queue {scalar_sum('rt_sched_queue_depth'):g}  |  "
        f"object store {int(scalar_sum('rt_object_store_used_bytes')):,} B  |  "
        f"channel write blocks {scalar_sum('rt_channel_write_blocks_total'):g}"
        f"  |  events dropped "
        f"{scalar_sum('rt_task_events_dropped_total'):g}"
    )
    # -- profiling / forensics status (one line; "off" when the
    # continuous sampler isn't running anywhere) --
    hz_series = metric("rt_profiler_hz")["series"].values()
    cont_hz = max(hz_series, default=0.0)
    samples = scalar_sum("rt_profile_samples_total")
    stalls = scalar_sum("rt_task_stalls_total")
    prof = (
        f"continuous @ {cont_hz:g} Hz, {int(samples):,} samples"
        if cont_hz > 0 else "continuous off"
    )
    out.append(
        f"profiling: {prof}  |  task stalls {int(stalls)}"
        + ("  <-- hung tasks flagged; run `rt stacks`" if stalls else "")
    )
    # -- bucketed grad sync (one line; only once a grad_sync has run) --
    overlap = metric("rt_collective_overlap_hidden_frac")["series"].values()
    ov_count = sum(v["count"] for v in overlap)
    ov_sum = sum(v["sum"] for v in overlap)
    bucket_b = scalar_sum("rt_collective_bucket_bytes_total")
    inter_b = scalar_sum("rt_collective_inter_host_bytes_total")
    if ov_count or bucket_b or inter_b:
        hidden = f"{ov_sum / ov_count * 100:.0f}%" if ov_count else "-"
        out.append(
            f"collectives: comm hidden {hidden} avg  |  bucket bytes "
            f"{int(bucket_b):,}  |  inter-host bytes {int(inter_b):,}"
        )

    # -- serve: one row per deployment --
    rows: dict = {}

    def row(dep: str) -> dict:
        return rows.setdefault(dep, {"deployment": dep})

    for dep, v in by_tag("rt_serve_router_requests_total",
                         "deployment").items():
        row(dep)["reqs"] = int(v)
    for dep, v in by_tag("rt_serve_tokens_generated_total",
                         "deployment").items():
        row(dep)["tokens"] = int(v)
    for dep, v in by_tag("rt_serve_kv_slots_occupied", "deployment").items():
        row(dep)["kv_slots"] = f"{v:g}"
    # paged engines: occupied/total pages + sealed prefix residents
    pg_occ = by_tag("rt_serve_kv_pages_occupied", "deployment")
    pg_tot = by_tag("rt_serve_kv_pages_total", "deployment")
    pg_res = by_tag("rt_serve_kv_pages_prefix_resident", "deployment")
    for dep in set(pg_occ) | set(pg_tot):
        cell = f"{pg_occ.get(dep, 0.0):g}"
        if pg_tot.get(dep):
            cell += f"/{pg_tot[dep]:g}"
        if dep in pg_res:
            cell += f" ({pg_res[dep]:g}pfx)"
        row(dep)["kv_pages"] = cell
    for dep, v in by_tag("rt_serve_queued_requests", "deployment").items():
        row(dep)["queued"] = f"{v:g}"
    for dep, h in hist_by_tag("rt_serve_ttft_s", "deployment").items():
        r = row(dep)
        r["ttft_p50_ms"] = ms(_hist_quantile(h["bounds"], h["buckets"], 0.5))
        r["ttft_p95_ms"] = ms(_hist_quantile(h["bounds"], h["buckets"], 0.95))
    for dep, h in hist_by_tag("rt_serve_inter_token_s", "deployment").items():
        row(dep)["itl_p50_ms"] = ms(
            _hist_quantile(h["bounds"], h["buckets"], 0.5)
        )
    for dep, h in hist_by_tag("rt_serve_decode_host_gap_s", "deployment").items():
        # host time the device sat idle between decode dispatches: ~0
        # when the async decode pipeline keeps a lookahead chunk in
        # flight, the per-chunk Python overhead when it does not
        row(dep)["host_gap_p95_ms"] = ms(
            _hist_quantile(h["bounds"], h["buckets"], 0.95)
        )
    for dep, h in hist_by_tag("rt_serve_batch_fill", "deployment").items():
        if h["count"]:
            row(dep)["batch_fill"] = f"{h['sum'] / h['count']:.1f}"
    hits = by_tag("rt_serve_prefix_cache_hits_total", "deployment")
    misses = by_tag("rt_serve_prefix_cache_misses_total", "deployment")
    for dep in set(hits) | set(misses):
        total = hits.get(dep, 0.0) + misses.get(dep, 0.0)
        if total:
            pct = f"{100.0 * hits.get(dep, 0.0) / total:.0f}%"
            row(dep)["cache_hit"] = pct
            # paged engines match PAGES, not host blocks: surface the
            # same ratio under the page-hit name next to kv_pages
            if dep in pg_occ or dep in pg_tot:
                row(dep)["page_hit"] = pct
    for dep, v in by_tag("rt_serve_shed_total", "deployment").items():
        if v:
            row(dep)["shed"] = int(v)
    for dep, st in (ascale or {}).items():
        r = row(dep)
        running = st.get("running", 0)
        target = st.get("target", 0)
        draining = len(st.get("draining") or {})
        rep = f"{running}/{target}"
        if draining:
            rep += f"(+{draining}d)"
        r["replicas"] = rep
        dec = st.get("last_decision") or {}
        if dec.get("direction") in ("up", "down"):
            r["last_scale"] = (
                f"{dec['direction']} {dec.get('from', '?')}->"
                f"{dec.get('to', '?')} {dec.get('reason', '')}"
            ).strip()
    for dep, r in rows.items():
        r["qps"] = (
            f"{qps.get(dep, 0.0):.1f}" if qps is not None else "-"
        )
    columns = ["deployment", "replicas", "reqs", "qps", "ttft_p50_ms",
               "ttft_p95_ms", "itl_p50_ms", "host_gap_p95_ms", "tokens",
               "kv_slots", "kv_pages", "queued", "shed", "batch_fill",
               "cache_hit",
               "page_hit", "last_scale"]
    if hist is not None:
        # windowed view from the history store: TTFT p95 over the last
        # --since seconds (not since boot) + a QPS sparkline
        win = hist.get("window_s", 60)
        for dep, h in hist.get("deployments", {}).items():
            r = row(dep)
            r[f"ttft_p95_{win:g}s_ms"] = ms(h.get("ttft_p95_s"))
            r["qps_hist"] = _sparkline(h.get("qps_points") or [])
        columns[columns.index("ttft_p95_ms") + 1:
                columns.index("ttft_p95_ms") + 1] = [
            f"ttft_p95_{hist.get('window_s', 60):g}s_ms", "qps_hist",
        ]
    out.append("")
    out.append("serve")
    out.append(_fmt_table([rows[d] for d in sorted(rows)], columns))

    # -- request summary: e2e / queue / exec percentiles per deployment --
    rrows = []
    for dep, entry in sorted((reqs.get("deployments") or {}).items()):
        e2e = entry.get("e2e_s") or {}
        rrows.append({
            "deployment": dep,
            "count": entry.get("count", 0),
            "e2e_p50_ms": ms(e2e.get("p50")),
            "e2e_p95_ms": ms(e2e.get("p95")),
            "e2e_p99_ms": ms(e2e.get("p99")),
            "queue_p50_ms": ms((entry.get("queue_s") or {}).get("p50")),
            "exec_p50_ms": ms((entry.get("exec_s") or {}).get("p50")),
        })
    out.append("")
    out.append("requests (traced)")
    out.append(_fmt_table(rrows, [
        "deployment", "count", "e2e_p50_ms", "e2e_p95_ms", "e2e_p99_ms",
        "queue_p50_ms", "exec_p50_ms",
    ]))

    # -- pipeline: bubble fraction + busy time per stage/schedule --
    m = metric("rt_pipeline_bubble_fraction")
    busy = hist_by_tag("rt_pipeline_stage_busy_s", "stage")
    prow: dict = {}
    for k, v in m["series"].items():
        t = tags(m, k)
        key = (t.get("stage") or "?", t.get("schedule") or "?")
        cur = prow.setdefault(
            key, {"stage": key[0], "schedule": key[1], "steps": 0,
                  "_sum": 0.0},
        )
        cur["steps"] += v["count"]
        cur["_sum"] += v["sum"]
    prows = []
    for key in sorted(prow):
        r = prow[key]
        r["bubble_pct"] = (
            f"{100.0 * r['_sum'] / r['steps']:.1f}" if r["steps"] else "-"
        )
        b = busy.get(r["stage"])
        r["busy_p50_ms"] = ms(
            _hist_quantile(b["bounds"], b["buckets"], 0.5) if b else None
        )
        prows.append(r)
    out.append("")
    out.append("pipeline")
    out.append(_fmt_table(prows, [
        "stage", "schedule", "steps", "bubble_pct", "busy_p50_ms",
    ]))
    if reqs.get("events_dropped"):
        out.append(
            f"warning: {reqs['events_dropped']} events dropped from "
            f"bounded buffers"
        )
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rt", description="ray_tpu cluster CLI"
    )
    from ray_tpu.utils.config import config

    parser.add_argument(
        "--address", default=(config.address or None),
        help="control store host:port (default: $RT_ADDRESS)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="cluster summary")
    listp = sub.add_parser("list", help="list cluster entities")
    listp.add_argument(
        "what",
        choices=["nodes", "actors", "jobs", "workers", "placement-groups"],
    )
    tl = sub.add_parser("timeline", help="dump a Chrome-trace timeline")
    tl.add_argument("--out", default="timeline.json")
    head = sub.add_parser("head", help="run / manage a standalone head")
    headsub = head.add_subparsers(dest="head_cmd", required=True)
    hs = headsub.add_parser("start", help="run the head in the foreground")
    hs.add_argument("--host", default="127.0.0.1")
    hs.add_argument("--port", type=int, default=0,
                    help="fix the port to make the head restartable in place")
    hs.add_argument("--session-id", default=None)
    hs.add_argument("--persist", default=None,
                    help="durable-log base path (snapshot + .wal)")
    hs.add_argument("--address-file", default=None,
                    help="publish the head address here for re-attach")
    sub.add_parser(
        "head-restart",
        help="bounce a standalone head in place (persist, re-exec, "
             "reconcile) — requires rt head start --persist + --port",
    )
    mem = sub.add_parser(
        "memory", help="per-node object-store contents + owner borrow "
                       "state (leaked-borrow triage)",
    )
    mem.add_argument("--min-bytes", type=int, default=0,
                     help="hide objects smaller than this")
    logs = sub.add_parser(
        "logs", help="tail worker stdout/stderr across the cluster",
    )
    logs.add_argument("job_id", nargs="?", default=None,
                      help="job to attribute (informational; all worker "
                           "logs of the session are shown)")
    logs.add_argument("--tail-bytes", type=int, default=4096)
    sub.add_parser(
        "summary",
        help="per-task queue-wait / exec latency percentiles",
    )
    sub.add_parser("metrics", help="aggregated metrics (Prometheus text)")
    sub.add_parser(
        "alerts",
        help="alert-rule states (SLO burn-rate / threshold rules over "
             "the head's metrics history); exits 2 while any rule fires",
    )
    top = sub.add_parser(
        "top",
        help="live serving / pipeline SLO view (QPS, TTFT, KV occupancy, "
             "bubble fraction, queue depths)",
    )
    top.add_argument("--interval", type=float, default=2.0,
                     help="refresh period in seconds")
    top.add_argument("--once", action="store_true",
                     help="render a single frame and exit (no screen "
                          "clearing; scriptable)")
    top.add_argument("--since", type=float, default=60.0,
                     help="trailing window (s) for the history-derived "
                          "columns (windowed TTFT p95, QPS sparkline)")
    prof = sub.add_parser(
        "profile",
        help="fleet-wide sampling profile: capture stacks on every live "
             "process for --duration seconds, merge, and report the "
             "per-subsystem split (+ folded stacks / flamegraph files)",
    )
    prof.add_argument("--duration", type=float, default=10.0,
                      help="capture window in seconds (server-capped by "
                           "RT_PROFILER_MAX_DURATION_S)")
    prof.add_argument("--hz", type=float, default=99.0,
                      help="sampling rate per process")
    prof.add_argument("--out", default="profile.folded",
                      help="write merged folded stacks here ('' to skip)")
    prof.add_argument("--html", default="profile.html",
                      help="write a self-contained flamegraph here "
                           "('' to skip)")
    stacks = sub.add_parser(
        "stacks",
        help="dump every thread's Python stack from every live process "
             "(hang triage; no restart, no signals)",
    )
    stacks.add_argument("--node", default=None,
                        help="node-id prefix: only that node's agent "
                             "and workers")
    pm = sub.add_parser(
        "postmortem",
        help="render crash flight-recorder black boxes (periodic "
             "snapshot of events/tasks/rss survives kill -9) and "
             "faulthandler crash files for dead processes",
    )
    pm.add_argument("target", nargs="?", default=None,
                    help="a pid or a node-id prefix (default: all)")
    pm.add_argument("--all", action="store_true", dest="show_alive",
                    help="include live processes, not just dead ones")
    dash = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    dash.add_argument("--port", type=int, default=8265)
    dash.add_argument(
        "--host", default="127.0.0.1",
        help="bind host (default loopback; the APIs are unauthenticated)",
    )
    job = sub.add_parser("job", help="submit / inspect cluster jobs")
    jobsub = job.add_subparsers(dest="job_cmd", required=True)
    js = jobsub.add_parser("submit")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run, e.g. -- python train.py")
    js.add_argument("--wait", action="store_true")
    for name in ("status", "logs", "stop"):
        p = jobsub.add_parser(name)
        p.add_argument("submission_id")
    jobsub.add_parser("list")
    args = parser.parse_args(argv)

    from ray_tpu import state

    addr = args.address
    if args.cmd == "head":
        if args.head_cmd == "start":
            from ray_tpu.core import head_main

            argv = ["--host", args.host, "--port", str(args.port)]
            if args.session_id:
                argv += ["--session-id", args.session_id]
            if args.persist:
                argv += ["--persist", args.persist]
            if args.address_file:
                argv += ["--address-file", args.address_file]
            head_main.main(argv)
            return 0
        return 1
    if args.cmd == "head-restart":
        from ray_tpu.utils.rpc import RemoteError, RpcClient

        if not addr:
            print("--address (or $RT_ADDRESS) required", file=sys.stderr)
            return 2
        client = RpcClient(addr, name="head-restart")
        try:
            client.call("head_restart", timeout_s=15.0)
            print(f"head at {addr} restarting (reconciliation follows)")
            return 0
        except RemoteError as e:
            if "no handler" in str(e):
                print(
                    "head-restart needs a standalone head "
                    "(`rt head start --persist ... --port ...`); this head "
                    "runs inside a driver process", file=sys.stderr,
                )
            else:
                print(f"head refused restart: {e}", file=sys.stderr)
            return 1
        finally:
            client.close()
    if args.cmd == "status":
        st = state.cluster_status(addr)
        if args.as_json:
            print(json.dumps(st, indent=2))
        else:
            res = st["resources_total"]
            avail = st["resources_available"]
            print(f"nodes: {st['nodes_alive']} alive, {st['nodes_dead']} dead")
            print(f"workers: {st['workers']}")
            ha = st.get("head_ha") or {}
            if ha.get("enabled"):
                line = (
                    f"head HA: durable log on (epoch {ha.get('epoch', 0)}, "
                    f"{ha.get('wal_since_snapshot', 0)} WAL entries since "
                    f"snapshot)"
                )
                if ha.get("recovering"):
                    line += (
                        f"; RECONCILING ({len(ha.get('unreconciled_nodes', []))} "
                        f"nodes pending, {ha.get('reconcile_remaining_s', 0):.1f}s "
                        f"left in window)"
                    )
                print(line)
            else:
                print("head HA: off (in-memory control store)")
            print(
                "actors: "
                + ", ".join(f"{k}={v}" for k, v in st["actors"].items())
            )
            for k in sorted(res):
                print(f"  {k}: {avail.get(k, 0.0):g}/{res[k]:g} available")
            obj = st["object_store"]
            print(
                f"object store: {obj['used_bytes']:,}/"
                f"{obj['capacity_bytes']:,} bytes used, "
                f"{obj['spilled_objects']} objects "
                f"({obj['spilled_bytes']:,} bytes) spilled"
            )
        return 0
    if args.cmd == "list":
        what = args.what
        fetch = {
            "nodes": (state.list_nodes, ["node_id", "address", "alive"]),
            "actors": (
                state.list_actors,
                ["actor_id", "class_name", "state", "name", "num_restarts"],
            ),
            "jobs": (state.list_jobs, ["job_id", "driver_address", "alive"]),
            "workers": (
                state.list_workers, ["worker_id", "node_id", "pid", "state"],
            ),
            "placement-groups": (
                state.list_placement_groups, ["pg_id", "strategy", "state"],
            ),
        }[what]
        rows = fetch[0](addr)
        if args.as_json:
            print(json.dumps(rows, indent=2, default=str))
        else:
            print(_fmt_table(rows, fetch[1]))
        return 0
    if args.cmd == "timeline":
        path = state.timeline(addr, out_path=args.out)
        print(f"wrote {path} (open in chrome://tracing or ui.perfetto.dev)")
        return 0
    if args.cmd == "memory":
        objs = [
            o for o in state.objects(addr)
            if (o.get("size") or 0) >= args.min_bytes or o.get("borrows")
            or o.get("inflight_pins")
        ]
        if args.as_json:
            print(json.dumps(objs, indent=2))
            return 0
        rows = []
        for o in objs:
            rows.append({
                "object_id": o["object_id"][:16],
                "node": (o.get("node_id") or "-")[:8],
                "location": o.get("location", "-"),
                "size": o.get("size") if o.get("size") is not None else "-",
                "state": o.get("state", "-"),
                "borrows": o.get("borrows", 0),
                "pins": o.get("inflight_pins", 0),
                "oldest_pin_s": (
                    f"{o['oldest_pin_age_s']:.1f}"
                    if o.get("oldest_pin_age_s") else "-"
                ),
            })
        print(_fmt_table(rows, [
            "object_id", "node", "location", "size", "state",
            "borrows", "pins", "oldest_pin_s",
        ]))
        leaked = {
            o["object_id"] for o in objs
            if o.get("oldest_pin_age_s", 0) > 60.0 and o.get("inflight_pins")
        }
        if leaked:
            print(
                f"warning: {len(leaked)} object(s) held by in-flight pins "
                f"older than 60s — likely leaked borrows"
            )
        return 0
    if args.cmd == "logs":
        logs = state.worker_logs(addr, tail_bytes=args.tail_bytes)
        if args.as_json:
            print(json.dumps(logs, indent=2))
            return 0
        if args.job_id:
            print(f"# worker logs (cluster-wide; job {args.job_id})")
        for entry in logs:
            if not entry["tail"]:
                continue
            who = entry.get("worker_id", entry["file"])
            print(
                f"==> node {entry['node_id'][:8]} {who} "
                f"[{entry['stream']}] <=="
            )
            print(entry["tail"], end="" if entry["tail"].endswith("\n") else "\n")
        return 0
    if args.cmd == "summary":
        summary = state.task_summary(addr)
        if args.as_json:
            print(json.dumps(summary, indent=2))
            return 0
        rows = []
        for name, entry in summary["tasks"].items():
            qw = entry.get("queue_wait_s")
            ex = entry["exec_s"]

            def ms(v):
                return f"{v * 1e3:.2f}"

            rows.append({
                "name": name,
                "count": entry["count"],
                "queue_p50_ms": ms(qw["p50"]) if qw else "-",
                "queue_p95_ms": ms(qw["p95"]) if qw else "-",
                "queue_p99_ms": ms(qw["p99"]) if qw else "-",
                "exec_p50_ms": ms(ex["p50"]),
                "exec_p95_ms": ms(ex["p95"]),
                "exec_p99_ms": ms(ex["p99"]),
            })
        print(_fmt_table(rows, [
            "name", "count", "queue_p50_ms", "queue_p95_ms",
            "queue_p99_ms", "exec_p50_ms", "exec_p95_ms", "exec_p99_ms",
        ]))
        if summary["events_dropped"]:
            print(
                f"warning: {summary['events_dropped']} events dropped from "
                f"bounded buffers — percentiles cover a truncated window"
            )
        return 0
    if args.cmd == "metrics":
        from ray_tpu.utils import metrics as metrics_mod

        print(metrics_mod.prometheus_text(state.cluster_metrics(addr)), end="")
        return 0
    if args.cmd == "alerts":
        from ray_tpu.utils.rpc import RemoteError

        try:
            rep = state.alerts(addr)
        except RemoteError:
            rep = {"enabled": False, "alerts": []}
        if args.as_json:
            print(json.dumps(rep, indent=2, default=str))
        elif not rep.get("enabled"):
            print("alerting disabled (RT_METRICS_SAMPLE_INTERVAL_S=0, "
                  "RT_ALERTS_ENABLED=0, or observability off)")
        else:
            rows = []
            for a in rep["alerts"]:
                rows.append({
                    "rule": a["name"],
                    "state": a["state"].upper()
                    if a["state"] == "firing" else a["state"],
                    "severity": a["severity"],
                    "metric": a["metric"],
                    "value": (
                        f"{a['value']:.4g}" if a.get("value") is not None
                        else "-"
                    ),
                    "since_s": (
                        f"{a['since_s']:.0f}" if a.get("since_s") is not None
                        else "-"
                    ),
                })
            print(_fmt_table(rows, [
                "rule", "state", "severity", "metric", "value", "since_s",
            ]))
        # scriptable: non-zero while anything fires (cron/CI gating)
        return 2 if any(
            a.get("state") == "firing" for a in rep.get("alerts", ())
        ) else 0
    if args.cmd == "top":
        import time as _time

        from ray_tpu.observability.history import counter_delta
        from ray_tpu.utils.rpc import RemoteError

        def frame(qps):
            mx = state.cluster_metrics(addr)
            reqs = state.request_summary(addr)
            try:
                alerts_rep = state.alerts(addr)
            except (RemoteError, RuntimeError):
                alerts_rep = {"enabled": False, "alerts": []}
            hist = _top_history(state, addr, args.since, _router_deps(mx))
            try:
                ascale = state.autoscale_status(addr)
            except Exception:  # noqa: BLE001 — no serve controller
                ascale = {}
            if args.as_json:
                return mx, json.dumps(
                    {"metrics": {
                        name: dict(m, series={
                            ",".join(k): v for k, v in m["series"].items()
                        }) for name, m in mx.items()
                    }, "requests": reqs, "alerts": alerts_rep,
                        "history": hist, "autoscale": ascale},
                    indent=2, default=str,
                )
            return mx, _render_top(mx, reqs, qps, alerts_rep=alerts_rep,
                                   hist=hist, ascale=ascale)

        if args.once:
            print(frame(None)[1])
            return 0
        prev: Optional[dict] = None
        prev_t = 0.0
        qps: Optional[dict] = None
        try:
            while True:
                mx, text = frame(qps)
                # QPS = reset-aware router-counter delta over the frame
                # gap (a restarted replica's counter going backwards
                # counts as a fresh start, not a zero-QPS frame)
                m = mx.get("rt_serve_router_requests_total") or {}
                cur = {}
                for k, v in m.get("series", {}).items():
                    dep = dict(
                        zip(m.get("tag_keys", ()), k)
                    ).get("deployment") or "?"
                    cur[dep] = cur.get(dep, 0.0) + v
                now = _time.monotonic()
                if prev is not None and now > prev_t:
                    qps = {
                        d: counter_delta(prev.get(d), v) / (now - prev_t)
                        for d, v in cur.items()
                    }
                prev, prev_t = cur, now
                sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
                sys.stdout.flush()
                _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    if args.cmd == "profile":
        from ray_tpu.observability import profiler as profiler_mod

        merged = state.profile(
            duration_s=args.duration, hz=args.hz, address=addr
        )
        if args.as_json:
            print(json.dumps(merged, indent=2))
            return 0
        print(
            f"profiled {merged['processes']}/{merged['targets']} processes "
            f"for {merged['duration_s']:g}s @ {merged['hz']:g} Hz — "
            f"{merged['samples']} thread samples"
        )
        print()
        print(profiler_mod.subsystem_table(merged["subsystems"]))
        if args.out:
            with open(args.out, "w") as f:
                f.write(profiler_mod.folded_text(merged["folded"]))
            print(f"\nwrote {args.out} (collapsed stacks; flamegraph.pl "
                  f"/ speedscope compatible)")
        if args.html:
            with open(args.html, "w") as f:
                f.write(profiler_mod.flamegraph_html(
                    merged["folded"],
                    title=f"rt profile — {merged['samples']} samples",
                ))
            print(f"wrote {args.html} (self-contained flamegraph)")
        return 0
    if args.cmd == "stacks":
        from ray_tpu.observability import forensics as forensics_mod

        dumps = state.stacks(address=addr, node=args.node)
        if args.as_json:
            print(json.dumps(dumps, indent=2))
            return 0
        if not dumps:
            print("no live processes reachable")
            return 1
        for dump in dumps:
            print(f"==> {dump.get('role', '?')} pid {dump.get('pid')} "
                  f"@ {dump.get('address')} <==")
            print(forensics_mod.format_stack_dump(dump))
            print()
        return 0
    if args.cmd == "postmortem":
        from ray_tpu.observability import forensics as forensics_mod

        pid = node = None
        if args.target:
            if args.target.isdigit():
                pid = int(args.target)
            else:
                node = args.target
        try:
            reports = state.crash_reports(address=addr, pid=pid, node=node)
        except RuntimeError:
            # no cluster reachable — scan this host's crash dirs directly
            # (the dead-cluster case is exactly when postmortems matter)
            reports = forensics_mod.list_crash_reports(pid=pid)
        if not args.show_alive:
            dead = [r for r in reports if not r.get("alive")]
            # with an explicit pid target show it regardless of liveness
            reports = reports if (pid is not None and not dead) else dead
        if args.as_json:
            print(json.dumps(reports, indent=2, default=str))
            return 0
        if not reports:
            print("no crash artifacts found"
                  + ("" if args.show_alive else " for dead processes "
                     "(--all includes live ones)"))
            return 0
        for rec in reports:
            print(forensics_mod.render_report(rec))
            print()
        return 0
    if args.cmd == "dashboard":
        import time as _time

        from ray_tpu.dashboard import Dashboard

        if not addr:
            print("--address (or $RT_ADDRESS) required", file=sys.stderr)
            return 2
        d = Dashboard(addr, host=args.host, port=args.port)
        d.start()
        print(f"dashboard serving on http://{d.address} (ctrl-c to stop)")
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            d.stop()
        return 0
    if args.cmd == "job":
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(addr)
        if args.job_cmd == "submit":
            import shlex

            entry = list(args.entrypoint)
            if entry and entry[0] == "--":  # strip ONLY the separator
                entry = entry[1:]
            if not entry:
                print("no entrypoint given", file=sys.stderr)
                return 2
            # shlex.join preserves quoting through the supervisor's shell
            sid = client.submit_job(entrypoint=shlex.join(entry))
            print(sid)
            if args.wait:
                status = client.wait_until_finished(sid)
                print(status)
                return 0 if status == "SUCCEEDED" else 1
            return 0
        if args.job_cmd == "status":
            print(client.get_job_status(args.submission_id))
            return 0
        if args.job_cmd == "logs":
            print(client.get_job_logs(args.submission_id), end="")
            return 0
        if args.job_cmd == "stop":
            print(client.stop_job(args.submission_id))
            return 0
        if args.job_cmd == "list":
            if args.as_json:
                print(json.dumps(client.list_jobs(), indent=2))
            else:
                print(_fmt_table(
                    client.list_jobs(),
                    ["submission_id", "status", "entrypoint"],
                ))
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
