"""rt — the cluster CLI.

Parity: the `ray` CLI's observability commands (reference
python/ray/scripts/scripts.py — status, list, timeline :2171). Run as
`python -m ray_tpu.cli <cmd>` (or `python -m ray_tpu <cmd>`); point it
at a cluster with --address or RT_ADDRESS.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _fmt_table(rows: List[dict], columns: List[str]) -> str:
    if not rows:
        return "(none)"
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in columns
    }
    head = "  ".join(c.upper().ljust(widths[c]) for c in columns)
    lines = [head, "-" * len(head)]
    for r in rows:
        lines.append(
            "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns)
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rt", description="ray_tpu cluster CLI"
    )
    parser.add_argument(
        "--address", default=os.environ.get("RT_ADDRESS"),
        help="control store host:port (default: $RT_ADDRESS)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="cluster summary")
    listp = sub.add_parser("list", help="list cluster entities")
    listp.add_argument(
        "what",
        choices=["nodes", "actors", "jobs", "workers", "placement-groups"],
    )
    tl = sub.add_parser("timeline", help="dump a Chrome-trace timeline")
    tl.add_argument("--out", default="timeline.json")
    sub.add_parser(
        "summary",
        help="per-task queue-wait / exec latency percentiles",
    )
    sub.add_parser("metrics", help="aggregated metrics (Prometheus text)")
    dash = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    dash.add_argument("--port", type=int, default=8265)
    dash.add_argument(
        "--host", default="127.0.0.1",
        help="bind host (default loopback; the APIs are unauthenticated)",
    )
    job = sub.add_parser("job", help="submit / inspect cluster jobs")
    jobsub = job.add_subparsers(dest="job_cmd", required=True)
    js = jobsub.add_parser("submit")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run, e.g. -- python train.py")
    js.add_argument("--wait", action="store_true")
    for name in ("status", "logs", "stop"):
        p = jobsub.add_parser(name)
        p.add_argument("submission_id")
    jobsub.add_parser("list")
    args = parser.parse_args(argv)

    from ray_tpu import state

    addr = args.address
    if args.cmd == "status":
        st = state.cluster_status(addr)
        if args.as_json:
            print(json.dumps(st, indent=2))
        else:
            res = st["resources_total"]
            avail = st["resources_available"]
            print(f"nodes: {st['nodes_alive']} alive, {st['nodes_dead']} dead")
            print(f"workers: {st['workers']}")
            print(
                "actors: "
                + ", ".join(f"{k}={v}" for k, v in st["actors"].items())
            )
            for k in sorted(res):
                print(f"  {k}: {avail.get(k, 0.0):g}/{res[k]:g} available")
            obj = st["object_store"]
            print(
                f"object store: {obj['used_bytes']:,}/"
                f"{obj['capacity_bytes']:,} bytes used, "
                f"{obj['spilled_objects']} objects "
                f"({obj['spilled_bytes']:,} bytes) spilled"
            )
        return 0
    if args.cmd == "list":
        what = args.what
        fetch = {
            "nodes": (state.list_nodes, ["node_id", "address", "alive"]),
            "actors": (
                state.list_actors,
                ["actor_id", "class_name", "state", "name", "num_restarts"],
            ),
            "jobs": (state.list_jobs, ["job_id", "driver_address", "alive"]),
            "workers": (
                state.list_workers, ["worker_id", "node_id", "pid", "state"],
            ),
            "placement-groups": (
                state.list_placement_groups, ["pg_id", "strategy", "state"],
            ),
        }[what]
        rows = fetch[0](addr)
        if args.as_json:
            print(json.dumps(rows, indent=2, default=str))
        else:
            print(_fmt_table(rows, fetch[1]))
        return 0
    if args.cmd == "timeline":
        path = state.timeline(addr, out_path=args.out)
        print(f"wrote {path} (open in chrome://tracing or ui.perfetto.dev)")
        return 0
    if args.cmd == "summary":
        summary = state.task_summary(addr)
        if args.as_json:
            print(json.dumps(summary, indent=2))
            return 0
        rows = []
        for name, entry in summary["tasks"].items():
            qw = entry.get("queue_wait_s")
            ex = entry["exec_s"]

            def ms(v):
                return f"{v * 1e3:.2f}"

            rows.append({
                "name": name,
                "count": entry["count"],
                "queue_p50_ms": ms(qw["p50"]) if qw else "-",
                "queue_p95_ms": ms(qw["p95"]) if qw else "-",
                "queue_p99_ms": ms(qw["p99"]) if qw else "-",
                "exec_p50_ms": ms(ex["p50"]),
                "exec_p95_ms": ms(ex["p95"]),
                "exec_p99_ms": ms(ex["p99"]),
            })
        print(_fmt_table(rows, [
            "name", "count", "queue_p50_ms", "queue_p95_ms",
            "queue_p99_ms", "exec_p50_ms", "exec_p95_ms", "exec_p99_ms",
        ]))
        if summary["events_dropped"]:
            print(
                f"warning: {summary['events_dropped']} events dropped from "
                f"bounded buffers — percentiles cover a truncated window"
            )
        return 0
    if args.cmd == "metrics":
        from ray_tpu.utils import metrics as metrics_mod

        print(metrics_mod.prometheus_text(state.cluster_metrics(addr)), end="")
        return 0
    if args.cmd == "dashboard":
        import time as _time

        from ray_tpu.dashboard import Dashboard

        if not addr:
            print("--address (or $RT_ADDRESS) required", file=sys.stderr)
            return 2
        d = Dashboard(addr, host=args.host, port=args.port)
        d.start()
        print(f"dashboard serving on http://{d.address} (ctrl-c to stop)")
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            d.stop()
        return 0
    if args.cmd == "job":
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(addr)
        if args.job_cmd == "submit":
            import shlex

            entry = list(args.entrypoint)
            if entry and entry[0] == "--":  # strip ONLY the separator
                entry = entry[1:]
            if not entry:
                print("no entrypoint given", file=sys.stderr)
                return 2
            # shlex.join preserves quoting through the supervisor's shell
            sid = client.submit_job(entrypoint=shlex.join(entry))
            print(sid)
            if args.wait:
                status = client.wait_until_finished(sid)
                print(status)
                return 0 if status == "SUCCEEDED" else 1
            return 0
        if args.job_cmd == "status":
            print(client.get_job_status(args.submission_id))
            return 0
        if args.job_cmd == "logs":
            print(client.get_job_logs(args.submission_id), end="")
            return 0
        if args.job_cmd == "stop":
            print(client.stop_job(args.submission_id))
            return 0
        if args.job_cmd == "list":
            if args.as_json:
                print(json.dumps(client.list_jobs(), indent=2))
            else:
                print(_fmt_table(
                    client.list_jobs(),
                    ["submission_id", "status", "entrypoint"],
                ))
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
