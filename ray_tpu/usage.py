"""Usage stats — opt-out local usage recording.

Parity: the reference's usage-stats subsystem (python/ray/_private/usage
— P17) without any network reporting: this environment has no egress, so
stats are recorded to a local JSON file for the operator's own
inspection. Disable entirely with RT_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

def _path() -> str:
    from ray_tpu.utils.config import config

    return os.path.join(str(config.temp_dir), "usage_stats.json")


def enabled() -> bool:
    from ray_tpu.utils.config import config

    return bool(config.usage_stats_enabled)


def record(event: str, **fields: Any) -> None:
    """Append one usage event (best-effort; never raises)."""
    if not enabled():
        return
    try:
        path = _path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        entry: Dict[str, Any] = {"event": event, "ts": time.time(), **fields}
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass


def read_all():
    try:
        with open(_path()) as f:
            return [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError):
        return []
