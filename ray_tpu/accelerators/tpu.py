"""TPU accelerator discovery + visibility management.

Parity: the reference's TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:291): chip-count discovery, GCE
metadata pod-type/topology/worker-id detection (:450-563), the
``TPU_VISIBLE_CHIPS`` visibility env, and the per-pod-type head resource
used for whole-slice gang scheduling (util/tpu.py:225,460).

Discovery order for chip count:
  1. RT_NUM_TPUS (explicit override; the config.num_tpus dynamic flag)
  2. TPU_VISIBLE_CHIPS env (visibility restriction)
  3. /dev/accel* or /dev/vfio device files (local chips)
  4. GCE TPU-VM metadata server (accelerator-type → chips per host)
None found → 0 (CPU-only node).

The RT_* overrides ride utils/config dynamic flags (re-read per call:
per-host inventory, never shipped in config snapshots).  The TPU_* /
PALLAS_* names are external contracts with the TPU runtime and stay raw
env reads.
"""

from __future__ import annotations

import glob
import json
import os
import urllib.request
from typing import List, Optional

from ray_tpu.utils.config import config

_GCE_METADATA_URL = "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"

# chips per host for common TPU VM generations
_CHIPS_PER_HOST = {
    "v2": 4, "v3": 4, "v4": 4, "v5litepod": 4, "v5e": 4, "v5p": 4, "v6e": 4,
}

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
NUM_TPUS_ENV = "RT_NUM_TPUS"


def _metadata(key: str) -> Optional[str]:
    if os.environ.get("TPU_SKIP_MDS_QUERY"):
        return None
    try:
        req = urllib.request.Request(
            _GCE_METADATA_URL + key, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=0.5) as resp:
            return resp.read().decode()
    except Exception:
        return None


class TPUAcceleratorManager:
    """Static discovery/visibility helpers (mirrors the reference's API)."""

    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_visible_accelerator_ids_env_var() -> str:
        return TPU_VISIBLE_CHIPS_ENV

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        explicit = config.num_tpus
        if explicit != "":
            return int(explicit)
        visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
        if visible:
            return len([c for c in visible.split(",") if c.strip()])
        devices = glob.glob("/dev/accel*") + glob.glob("/dev/vfio/[0-9]*")
        if devices:
            return len(devices)
        accel_type = _metadata("accelerator-type")  # e.g. "v5litepod-16"
        if accel_type:
            gen = accel_type.split("-")[0]
            return _CHIPS_PER_HOST.get(gen, 4)
        # Pallas/axon tunnel (this dev environment): one remote chip.
        if os.environ.get("PALLAS_AXON_TPU_GEN"):
            return 1
        return 0

    @staticmethod
    def get_current_pod_type() -> Optional[str]:
        """e.g. 'v5litepod-16' — the accelerator-type of the slice."""
        env = config.tpu_pod_type
        if env:
            return env
        accel_type = _metadata("accelerator-type")
        if accel_type:
            return accel_type
        gen = os.environ.get("PALLAS_AXON_TPU_GEN")
        if gen:
            return gen
        return None

    @staticmethod
    def get_current_topology() -> Optional[str]:
        env = config.tpu_topology
        if env:
            return env
        return _metadata("tpu-env") and _parse_tpu_env("TOPOLOGY") or None

    @staticmethod
    def get_current_worker_id() -> Optional[int]:
        env = config.tpu_worker_id
        if env != "":
            return int(env)
        wid = _metadata("agent-worker-number")
        if wid is not None:
            try:
                return int(wid)
            except ValueError:
                return None
        return None

    @staticmethod
    def set_visible_accelerator_ids(ids: List[str]) -> None:
        os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(ids)

    @staticmethod
    def num_workers_in_slice(pod_type: str) -> int:
        """Hosts in a slice, from the pod type (e.g. v5litepod-16 → 4 hosts)."""
        try:
            gen, chips = pod_type.rsplit("-", 1)
            per_host = _CHIPS_PER_HOST.get(gen.split("_")[0], 4)
            return max(1, int(chips) // per_host)
        except (ValueError, KeyError):
            return 1


def _parse_tpu_env(key: str) -> Optional[str]:
    raw = _metadata("tpu-env")
    if not raw:
        return None
    try:
        for line in raw.splitlines():
            if line.startswith(key):
                return line.split(":", 1)[1].strip().strip("'\"")
    except Exception:
        return None
    return None


def get_tpu_coordinator_env_vars(
    coordinator_address: str, num_slices: int, slice_id: int
) -> dict:
    """MEGASCALE env for DCN multislice meshes.

    Parity: ray.util.tpu.get_tpu_coordinator_env_vars (util/tpu.py:198) —
    the env that makes XLA build a hierarchical ICI(inner)/DCN(outer) mesh.
    """
    return {
        "MEGASCALE_COORDINATOR_ADDRESS": coordinator_address,
        "MEGASCALE_NUM_SLICES": str(num_slices),
        "MEGASCALE_SLICE_ID": str(slice_id),
        "MEGASCALE_PORT": "8081",
    }
