"""Whole-slice gang scheduling: SlicePlacementGroup.

Parity: ray.util.tpu.SlicePlacementGroup / slice_placement_group
(reference python/ray/util/tpu.py:225,460 + reserve_tpu_slice
accelerators/tpu.py:237): a multi-host TPU slice is reserved as ONE
atom — bundle 0 claims the slice's "TPU-{pod_type}-head" resource (only
worker 0 of a slice advertises it, accelerators/__init__.py), the
remaining bundles claim each host's chips, and STRICT_SPREAD pins one
bundle per host. Train worker groups then land one worker per slice
host, which is exactly the "1 worker = 1 host = N chips" model the JAX
backend needs (SURVEY §7 hard part e).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.accelerators.tpu import (
    TPUAcceleratorManager,
    get_tpu_coordinator_env_vars,
)
from ray_tpu.core.placement import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    placement_group,
)


class SlicePlacementGroup:
    """A reserved TPU slice: one placement-group bundle per slice host."""

    def __init__(
        self,
        pod_type: str,
        chips_per_host: int = 4,
        num_slices: int = 1,
        name: Optional[str] = None,
    ):
        self.pod_type = pod_type
        self.chips_per_host = chips_per_host
        self.num_slices = num_slices
        self.num_workers_per_slice = TPUAcceleratorManager.num_workers_in_slice(
            pod_type
        )
        bundles: List[Dict[str, float]] = []
        for _ in range(num_slices):
            bundles.append(
                {f"TPU-{pod_type}-head": 1.0, "TPU": float(chips_per_host)}
            )
            bundles.extend(
                {"TPU": float(chips_per_host)}
                for _ in range(self.num_workers_per_slice - 1)
            )
        self._pg = placement_group(bundles, strategy="STRICT_SPREAD", name=name)

    @property
    def placement_group(self) -> PlacementGroup:
        return self._pg

    @property
    def num_workers(self) -> int:
        return self.num_slices * self.num_workers_per_slice

    def wait(self, timeout_seconds: float = 120.0) -> bool:
        return self._pg.wait(timeout_seconds)

    def ready(self):
        return self._pg.ready()

    def worker_strategy(
        self, slice_id: int, worker_id: int
    ) -> PlacementGroupSchedulingStrategy:
        """Scheduling strategy pinning (slice_id, worker_id) to its host's
        bundle (bundle 0 of each slice = the head host)."""
        idx = slice_id * self.num_workers_per_slice + worker_id
        return PlacementGroupSchedulingStrategy(
            placement_group=self._pg, placement_group_bundle_index=idx
        )

    def coordinator_env(
        self, coordinator_address: str, slice_id: int
    ) -> Dict[str, str]:
        """MEGASCALE env for this slice's workers (DCN multislice)."""
        return get_tpu_coordinator_env_vars(
            coordinator_address, self.num_slices, slice_id
        )

    def remove(self) -> None:
        from ray_tpu.core.placement import remove_placement_group

        remove_placement_group(self._pg)


def slice_placement_group(
    pod_type: str,
    chips_per_host: int = 4,
    num_slices: int = 1,
    name: Optional[str] = None,
) -> SlicePlacementGroup:
    """Reserve `num_slices` whole TPU slices of `pod_type` (parity:
    ray.util.tpu.slice_placement_group, util/tpu.py:460)."""
    return SlicePlacementGroup(pod_type, chips_per_host, num_slices, name)
