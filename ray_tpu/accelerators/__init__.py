"""Accelerator discovery plugins.

Parity: the reference's per-vendor accelerator managers
(python/ray/_private/accelerators/__init__.py). Here TPU is the first-class
citizen; a generic CPU fallback covers everything else.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from ray_tpu.accelerators.tpu import TPUAcceleratorManager
from ray_tpu.utils.config import config


def detect_node_resources_and_labels() -> Tuple[Dict[str, float], Dict[str, str]]:
    """Resources + labels this host contributes to the cluster."""
    resources: Dict[str, float] = {
        "CPU": float(config.num_cpus or os.cpu_count() or 1),
        "memory": float(_total_memory_bytes()),
    }
    labels: Dict[str, str] = {}
    tpu = TPUAcceleratorManager
    num_chips = tpu.get_current_node_num_accelerators()
    if num_chips > 0:
        resources["TPU"] = float(num_chips)
        pod_type = tpu.get_current_pod_type()
        if pod_type:
            labels["tpu-pod-type"] = pod_type
            # Whole-slice gang scheduling marker (reference: the
            # "TPU-{pod_type}-head" resource, accelerators/tpu.py:450-563).
            if tpu.get_current_worker_id() in (None, 0):
                resources[f"TPU-{pod_type}-head"] = 1.0
        topology = tpu.get_current_topology()
        if topology:
            labels["tpu-topology"] = topology
        worker_id = tpu.get_current_worker_id()
        if worker_id is not None:
            labels["tpu-worker-id"] = str(worker_id)
    return resources, labels


def _total_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30


def __getattr__(name):
    # SlicePlacementGroup lives in its own module to keep discovery
    # import-light (it pulls in the placement API).
    if name in ("SlicePlacementGroup", "slice_placement_group"):
        from ray_tpu.accelerators import slice_pg

        return getattr(slice_pg, name)
    raise AttributeError(f"module 'ray_tpu.accelerators' has no attribute {name!r}")
