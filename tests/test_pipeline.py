"""Pipeline parallelism + shm channel tests (parity model: the
reference's compiled-graph PP loops, python/ray/dag/tests)."""

import os
import signal
import threading
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _two_stage_problem():
    """A 2-layer MLP regression split into two pipeline stages."""
    import numpy as np

    rng = np.random.default_rng(0)
    W1 = rng.normal(size=(8, 16)).astype(np.float32) * 0.3
    W2 = rng.normal(size=(16, 4)).astype(np.float32) * 0.3
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = rng.normal(size=(32, 4)).astype(np.float32)

    def stage1(params, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ params["w"])

    def stage2(params, h):
        return h @ params["w"]

    def loss_fn(pred, target):
        import jax.numpy as jnp

        return jnp.mean((pred - target) ** 2)

    return W1, W2, X, Y, stage1, stage2, loss_fn


def _reference_step(W1, W2, X, Y, lr, n_mb):
    """Unpipelined equivalent: mean of microbatch grads, one SGD step."""
    import jax
    import jax.numpy as jnp

    def full_loss(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean((h @ params["w2"] - y) ** 2)

    params = {"w1": jnp.asarray(W1), "w2": jnp.asarray(W2)}
    mbs = np.split(X, n_mb)
    tgts = np.split(Y, n_mb)
    grads = None
    losses = []
    for x, y in zip(mbs, tgts):
        loss, g = jax.value_and_grad(full_loss)(params, x, y)
        losses.append(float(loss))
        grads = g if grads is None else jax.tree.map(
            lambda a, b: a + b, grads, g
        )
    grads = jax.tree.map(lambda g: g / n_mb, grads)
    params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return params, sum(losses) / n_mb


def test_gpipe_matches_unpipelined(rt):
    from ray_tpu.parallel.pipeline import Pipeline

    W1, W2, X, Y, stage1, stage2, loss_fn = _two_stage_problem()
    pipe = Pipeline(
        [stage1, stage2],
        [{"w": W1}, {"w": W2}],
        loss_fn,
    )
    try:
        n_mb, lr = 4, 0.1
        loss = pipe.train_step(
            list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
        )
        ref_params, ref_loss = _reference_step(W1, W2, X, Y, lr, n_mb)
        # driver-side reference runs on the TPU backend (bf16 matmul default)
        # while stages run on CPU workers: tolerances are bf16-scale
        assert abs(loss - ref_loss) < 5e-3
        p1, p2 = pipe.get_params()
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(ref_params["w1"]),
            rtol=5e-3, atol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(p2["w"]), np.asarray(ref_params["w2"]),
            rtol=5e-3, atol=5e-4,
        )
        # a few more steps actually reduce the loss
        first = loss
        for _ in range(5):
            loss = pipe.train_step(
                list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
            )
        assert loss < first
        # inference path
        out = pipe.forward(X[:4])
        assert np.asarray(out).shape == (4, 4)
    finally:
        pipe.shutdown()


# -- compiled tier: 1F1B/GPipe over seqlock channels ---------------------


def test_schedule_ops_properties():
    """Every (F,k)/(B,k) appears exactly once, backwards run in
    microbatch order at every stage (the bit-for-bit guarantee), and
    1F1B's peak live activations match min(n_mb, n_stages - stage) vs
    GPipe's n_mb."""
    from ray_tpu.parallel.pipeline import (
        _max_live_activations, _schedule_ops,
    )

    for schedule in ("gpipe", "1f1b"):
        for n_stages in (1, 2, 4):
            for n_mb in (1, 3, 8):
                for stage in range(n_stages):
                    ops = _schedule_ops(schedule, n_stages, stage, n_mb)
                    fwd = [k for op, k in ops if op == "F"]
                    bwd = [k for op, k in ops if op == "B"]
                    assert fwd == list(range(n_mb))
                    assert bwd == list(range(n_mb))
                    # a backward can never precede its own forward
                    seen_f = set()
                    for op, k in ops:
                        if op == "F":
                            seen_f.add(k)
                        else:
                            assert k in seen_f
    # the 1F1B memory claim
    assert _max_live_activations("gpipe", 4, 0, 8) == 8
    assert _max_live_activations("1f1b", 4, 0, 8) == 4
    assert _max_live_activations("1f1b", 4, 3, 8) == 1
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        _schedule_ops("pipedream", 2, 0, 4)


def test_compiled_gpipe_matches_unpipelined(rt):
    from ray_tpu.parallel.pipeline import Pipeline

    W1, W2, X, Y, stage1, stage2, loss_fn = _two_stage_problem()
    pipe = Pipeline([stage1, stage2], [{"w": W1}, {"w": W2}], loss_fn)
    cp = pipe.compile(schedule="gpipe", step_timeout_s=60.0)
    try:
        n_mb, lr = 4, 0.1
        loss = cp.train_step(
            list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
        )
        ref_params, ref_loss = _reference_step(W1, W2, X, Y, lr, n_mb)
        assert abs(loss - ref_loss) < 5e-3
        p1, p2 = cp.get_params()
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(ref_params["w1"]),
            rtol=5e-3, atol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(p2["w"]), np.asarray(ref_params["w2"]),
            rtol=5e-3, atol=5e-4,
        )
        first = loss
        for _ in range(5):
            loss = cp.train_step(
                list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
            )
        assert loss < first
    finally:
        cp.teardown(timeout_s=30.0)
        pipe.shutdown()


def test_compiled_1f1b_matches_gpipe_bitwise(rt):
    """The headline 1F1B guarantee: identical microbatch computations in
    identical backward order — the post-step params are BIT-IDENTICAL
    to GPipe's (and the losses match exactly)."""
    from ray_tpu.parallel.pipeline import Pipeline

    W1, W2, X, Y, stage1, stage2, loss_fn = _two_stage_problem()
    results = {}
    for sched in ("gpipe", "1f1b"):
        pipe = Pipeline([stage1, stage2], [{"w": W1}, {"w": W2}], loss_fn)
        cp = pipe.compile(schedule=sched, step_timeout_s=60.0)
        try:
            losses = [
                cp.train_step(
                    list(np.split(X, 8)), list(np.split(Y, 8)), lr=0.1
                )
                for _ in range(2)
            ]
            results[sched] = (losses, cp.get_params())
        finally:
            cp.teardown(timeout_s=30.0)
            pipe.shutdown()
    g_losses, g_params = results["gpipe"]
    o_losses, o_params = results["1f1b"]
    assert g_losses == o_losses  # exact float equality
    for gp, op in zip(g_params, o_params):
        np.testing.assert_array_equal(
            np.asarray(gp["w"]), np.asarray(op["w"])
        )


def test_compiled_pipeline_rpc_channel_tier(rt):
    """Force every stage boundary onto the cross-host RpcChannel tier
    (worker<->worker chan_push, out-of-band multiseg payloads) — the
    numbers must match the shm tier exactly."""
    from ray_tpu.parallel.pipeline import Pipeline
    from ray_tpu.utils.config import config

    W1, W2, X, Y, stage1, stage2, loss_fn = _two_stage_problem()
    pipe = Pipeline([stage1, stage2], [{"w": W1}, {"w": W2}], loss_fn)
    config.set("pipeline_force_rpc_channels", True)
    try:
        cp = pipe.compile(schedule="1f1b", step_timeout_s=60.0)
    finally:
        config.set("pipeline_force_rpc_channels", False)
    try:
        n_mb, lr = 4, 0.1
        loss = cp.train_step(
            list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
        )
        _, ref_loss = _reference_step(W1, W2, X, Y, lr, n_mb)
        assert abs(loss - ref_loss) < 5e-3
        loss2 = cp.train_step(
            list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
        )
        assert loss2 < loss
    finally:
        cp.teardown(timeout_s=30.0)
        pipe.shutdown()


def test_compiled_pipeline_chaos_sigkill_mid_step(rt):
    """SIGKILL a MID-pipeline stage during a 1F1B step: the driver must
    raise within the step deadline (no hang), and teardown must still
    reclaim every channel (no /dev/shm/rtchan_* debris)."""
    from ray_tpu.parallel.pipeline import Pipeline

    rng = np.random.default_rng(1)
    Ws = [rng.normal(size=(8, 8)).astype(np.float32) * 0.3
          for _ in range(3)]
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = rng.normal(size=(32, 8)).astype(np.float32)

    def slow_stage(params, x):
        import time

        import jax.numpy as jnp

        time.sleep(0.05)  # stretch the step so the kill lands MID-step
        return jnp.tanh(x @ params["w"])

    def loss_fn(pred, target):
        import jax.numpy as jnp

        return jnp.mean((pred - target) ** 2)

    pipe = Pipeline([slow_stage] * 3, [{"w": w} for w in Ws], loss_fn)
    victim_pid = ray_tpu.get(pipe.stages[1].pid.remote(), timeout=30)
    cp = pipe.compile(schedule="1f1b", step_timeout_s=8.0)
    shm_paths = [ch.path for ch in cp._shm_channels]
    assert shm_paths, "expected shm channels on the same-host pipeline"

    killer = threading.Timer(
        0.3, lambda: os.kill(victim_pid, signal.SIGKILL)
    )
    killer.start()
    t0 = time.monotonic()
    try:
        with pytest.raises(Exception):
            cp.train_step(list(np.split(X, 8)), list(np.split(Y, 8)),
                          lr=0.1)
        # raised within the op deadline (plus slack), not a hang
        assert time.monotonic() - t0 < 20.0
        # broken pipeline refuses further steps
        with pytest.raises(RuntimeError, match="broken"):
            cp.train_step(list(np.split(X, 8)), list(np.split(Y, 8)))
    finally:
        killer.cancel()
        cp.teardown(timeout_s=15.0)
        pipe.shutdown()
    for p in shm_paths:
        assert not os.path.exists(p), f"teardown leaked {p}"
        assert not os.path.exists(p + ".d")


def test_rpc_mailbox_semantics(rt):
    """RpcChannel receiver mailbox: bounded, idempotent per seq, and
    closed STAYS closed (a writer retry racing close must bounce, not
    silently recreate an orphan mailbox)."""
    from ray_tpu.core.channels import (
        close_rpc_mailbox, rpc_channel_deliver,
    )

    cid = "rtchan_test_mailbox"
    assert rpc_channel_deliver(cid, 1, b"a", 2)["status"] == "ok"
    assert rpc_channel_deliver(cid, 1, b"a", 2)["status"] == "ok"  # dup
    assert rpc_channel_deliver(cid, 2, b"b", 2)["status"] == "ok"
    assert rpc_channel_deliver(cid, 3, b"c", 2)["status"] == "full"
    from ray_tpu.core import channels as channels_mod

    mb = channels_mod._mailbox(cid, 2)
    with mb.cv:
        mb.q.popleft()
        mb.consumed += 1
    assert rpc_channel_deliver(cid, 3, b"c", 2)["status"] == "ok"
    close_rpc_mailbox(cid)
    # tombstoned: late writer retries bounce forever (chan ids are
    # one-shot uuids, never legitimately reused)
    assert rpc_channel_deliver(cid, 4, b"d", 2)["status"] == "closed"
    close_rpc_mailbox(cid)  # idempotent


def test_shm_channel_roundtrip(rt):
    """Mutable shm channel between two actors on the same host."""

    @ray_tpu.remote
    class Producer:
        def __init__(self, handle):
            from ray_tpu.core.channels import ShmChannel

            self.ch = ShmChannel.from_handle(handle)

        def send(self, n):
            import time

            for i in range(n):
                self.ch.write(f"msg-{i}".encode())
                # slot channel (no backpressure): pace lightly so the
                # reader observes most messages
                time.sleep(0.002)
            return True

    @ray_tpu.remote
    class Consumer:
        def __init__(self, handle):
            from ray_tpu.core.channels import ShmChannel

            self.ch = ShmChannel.from_handle(handle)

        def recv(self, n):
            # read until the final message: a slot channel may skip
            # intermediate messages if the reader lags the writer
            out = []
            while True:
                m = self.ch.read(timeout_s=30).decode()
                out.append(m)
                if m == f"msg-{n - 1}":
                    return out

    from ray_tpu.core.channels import ShmChannel

    ch = ShmChannel.create(capacity=1024)
    try:
        prod = Producer.remote(ch.handle())
        cons = Consumer.remote(ch.handle())
        n = 50
        recv_ref = cons.recv.remote(n)
        send_ref = prod.send.remote(n)
        got = ray_tpu.get(recv_ref, timeout=60)
        assert ray_tpu.get(send_ref, timeout=60)
        assert 1 <= len(got) <= n
        # SPSC slot semantics: messages arrive in order (some may be
        # skipped if the reader lags; the final message always lands)
        idxs = [int(m.split("-")[1]) for m in got]
        assert idxs == sorted(idxs)
        assert idxs[-1] == n - 1
    finally:
        ch.close(unlink=True)
