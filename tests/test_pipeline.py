"""Pipeline parallelism + shm channel tests (parity model: the
reference's compiled-graph PP loops, python/ray/dag/tests)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _two_stage_problem():
    """A 2-layer MLP regression split into two pipeline stages."""
    import numpy as np

    rng = np.random.default_rng(0)
    W1 = rng.normal(size=(8, 16)).astype(np.float32) * 0.3
    W2 = rng.normal(size=(16, 4)).astype(np.float32) * 0.3
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = rng.normal(size=(32, 4)).astype(np.float32)

    def stage1(params, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ params["w"])

    def stage2(params, h):
        return h @ params["w"]

    def loss_fn(pred, target):
        import jax.numpy as jnp

        return jnp.mean((pred - target) ** 2)

    return W1, W2, X, Y, stage1, stage2, loss_fn


def _reference_step(W1, W2, X, Y, lr, n_mb):
    """Unpipelined equivalent: mean of microbatch grads, one SGD step."""
    import jax
    import jax.numpy as jnp

    def full_loss(params, x, y):
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean((h @ params["w2"] - y) ** 2)

    params = {"w1": jnp.asarray(W1), "w2": jnp.asarray(W2)}
    mbs = np.split(X, n_mb)
    tgts = np.split(Y, n_mb)
    grads = None
    losses = []
    for x, y in zip(mbs, tgts):
        loss, g = jax.value_and_grad(full_loss)(params, x, y)
        losses.append(float(loss))
        grads = g if grads is None else jax.tree.map(
            lambda a, b: a + b, grads, g
        )
    grads = jax.tree.map(lambda g: g / n_mb, grads)
    params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    return params, sum(losses) / n_mb


def test_gpipe_matches_unpipelined(rt):
    from ray_tpu.parallel.pipeline import Pipeline

    W1, W2, X, Y, stage1, stage2, loss_fn = _two_stage_problem()
    pipe = Pipeline(
        [stage1, stage2],
        [{"w": W1}, {"w": W2}],
        loss_fn,
    )
    try:
        n_mb, lr = 4, 0.1
        loss = pipe.train_step(
            list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
        )
        ref_params, ref_loss = _reference_step(W1, W2, X, Y, lr, n_mb)
        # driver-side reference runs on the TPU backend (bf16 matmul default)
        # while stages run on CPU workers: tolerances are bf16-scale
        assert abs(loss - ref_loss) < 5e-3
        p1, p2 = pipe.get_params()
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(ref_params["w1"]),
            rtol=5e-3, atol=5e-4,
        )
        np.testing.assert_allclose(
            np.asarray(p2["w"]), np.asarray(ref_params["w2"]),
            rtol=5e-3, atol=5e-4,
        )
        # a few more steps actually reduce the loss
        first = loss
        for _ in range(5):
            loss = pipe.train_step(
                list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=lr
            )
        assert loss < first
        # inference path
        out = pipe.forward(X[:4])
        assert np.asarray(out).shape == (4, 4)
    finally:
        pipe.shutdown()


def test_shm_channel_roundtrip(rt):
    """Mutable shm channel between two actors on the same host."""

    @ray_tpu.remote
    class Producer:
        def __init__(self, handle):
            from ray_tpu.core.channels import ShmChannel

            self.ch = ShmChannel.from_handle(handle)

        def send(self, n):
            import time

            for i in range(n):
                self.ch.write(f"msg-{i}".encode())
                # slot channel (no backpressure): pace lightly so the
                # reader observes most messages
                time.sleep(0.002)
            return True

    @ray_tpu.remote
    class Consumer:
        def __init__(self, handle):
            from ray_tpu.core.channels import ShmChannel

            self.ch = ShmChannel.from_handle(handle)

        def recv(self, n):
            # read until the final message: a slot channel may skip
            # intermediate messages if the reader lags the writer
            out = []
            while True:
                m = self.ch.read(timeout_s=30).decode()
                out.append(m)
                if m == f"msg-{n - 1}":
                    return out

    from ray_tpu.core.channels import ShmChannel

    ch = ShmChannel.create(capacity=1024)
    try:
        prod = Producer.remote(ch.handle())
        cons = Consumer.remote(ch.handle())
        n = 50
        recv_ref = cons.recv.remote(n)
        send_ref = prod.send.remote(n)
        got = ray_tpu.get(recv_ref, timeout=60)
        assert ray_tpu.get(send_ref, timeout=60)
        assert 1 <= len(got) <= n
        # SPSC slot semantics: messages arrive in order (some may be
        # skipped if the reader lags; the final message always lands)
        idxs = [int(m.split("-")[1]) for m in got]
        assert idxs == sorted(idxs)
        assert idxs[-1] == n - 1
    finally:
        ch.close(unlink=True)
