"""Multi-node-on-one-machine tests (parity model: reference tests using
python/ray/cluster_utils.py Cluster, e.g. test_placement_group_2.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster
from ray_tpu.core.placement import PlacementGroupSchedulingStrategy


@pytest.fixture
def cluster():
    c = Cluster()
    yield c
    try:
        ray_tpu.shutdown()
    finally:
        c.shutdown()


def test_multi_node_spread(cluster):
    cluster.add_node(num_cpus=2, resources={"tag_a": 1})
    cluster.add_node(num_cpus=2, resources={"tag_b": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    # custom-resource targeting lands tasks on specific nodes
    a = ray_tpu.get(where.options(resources={"tag_a": 1}).remote())
    b = ray_tpu.get(where.options(resources={"tag_b": 1}).remote())
    assert a != b
    assert {a, b} == {n.node_id for n in cluster.nodes}


def test_strict_spread_pg_across_nodes(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.wait(20)
    locs = pg.table()["bundle_locations"]
    assert len(set(locs.values())) == 2


def test_actor_survives_node_death(cluster):
    cluster.add_node(num_cpus=2, resources={"pin": 1})
    victim = cluster.add_node(num_cpus=2, resources={"doomed": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    class Stateful:
        def node(self):
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

    a = Stateful.options(
        max_restarts=-1, resources={"CPU": 1}
    ).remote()
    first_node = ray_tpu.get(a.node.remote(), timeout=60)

    if first_node == victim.node_id:
        cluster.kill_node(victim)
        # in-flight/new calls should eventually reach the restarted actor
        deadline = time.monotonic() + 60
        second_node = None
        while time.monotonic() < deadline:
            try:
                second_node = ray_tpu.get(a.node.remote(), timeout=15)
                break
            except Exception:
                time.sleep(0.3)
        assert second_node is not None and second_node != victim.node_id
    else:
        # actor landed on the survivor; killing the other node must not hurt
        cluster.kill_node(victim)
        assert ray_tpu.get(a.node.remote(), timeout=30) == first_node
