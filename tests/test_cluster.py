"""Multi-node-on-one-machine tests (parity model: reference tests using
python/ray/cluster_utils.py Cluster, e.g. test_placement_group_2.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster
from ray_tpu.core.placement import PlacementGroupSchedulingStrategy


@pytest.fixture(scope="module")
def _shared_cluster():
    # ONE head for the whole module (the other of the tier-1 sweep's
    # two slowest cluster spinners): tests add nodes under test-unique
    # resource tags and kill only nodes they added, so sharing the head
    # never leaks scheduling surface between tests.
    c = Cluster()
    try:
        yield c
    finally:
        c.shutdown()


@pytest.fixture
def cluster(_shared_cluster):
    try:
        yield _shared_cluster
    finally:
        ray_tpu.shutdown()


def test_multi_node_spread(cluster):
    node_a = cluster.add_node(num_cpus=2, resources={"tag_a": 1})
    node_b = cluster.add_node(num_cpus=2, resources={"tag_b": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    # custom-resource targeting lands tasks on specific nodes
    a = ray_tpu.get(where.options(resources={"tag_a": 1}).remote())
    b = ray_tpu.get(where.options(resources={"tag_b": 1}).remote())
    assert a != b
    assert {a, b} == {node_a.node_id, node_b.node_id}


def test_strict_spread_pg_across_nodes(cluster):
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.wait(20)
    locs = pg.table()["bundle_locations"]
    assert len(set(locs.values())) == 2


def test_pg_bundle_task_on_remote_node(cluster):
    """Tasks pinned to a PG bundle hosted on a different node than the
    caller's local agent must spill back to the bundle's node, not hang
    (ADVICE r1 high finding)."""
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.wait(20)
    locs = pg.table()["bundle_locations"]

    @ray_tpu.remote
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    for idx in (0, 1):
        node = ray_tpu.get(
            where.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=idx
                )
            ).remote(),
            timeout=60,
        )
        assert node == locs[idx]


def test_cross_node_large_object_get(cluster):
    """A borrower on a different host can read a >max_direct object: the
    owner's reply routes through the hosting agent's chunked read instead
    of handing back a useless local shm path (ADVICE r1 medium finding)."""
    import numpy as np

    cluster.add_node(num_cpus=2, resources={"site_a": 1})
    cluster.add_node(num_cpus=2, resources={"site_b": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(resources={"site_a": 1})
    def produce():
        return np.arange(1_000_000, dtype=np.int64)  # ~8MB, plasma-backed

    @ray_tpu.remote(resources={"site_b": 1})
    def consume(arr):
        return int(arr.sum())

    ref = produce.remote()
    got = ray_tpu.get(consume.remote(ref), timeout=90)
    assert got == 499999500000


def test_actor_survives_node_death(cluster):
    cluster.add_node(num_cpus=2, resources={"pin": 1})
    victim = cluster.add_node(num_cpus=2, resources={"doomed": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    class Stateful:
        def node(self):
            import ray_tpu as rt

            return rt.get_runtime_context().get_node_id()

    a = Stateful.options(
        max_restarts=-1, resources={"CPU": 1}
    ).remote()
    first_node = ray_tpu.get(a.node.remote(), timeout=60)

    if first_node == victim.node_id:
        cluster.kill_node(victim)
        # in-flight/new calls should eventually reach the restarted actor
        deadline = time.monotonic() + 60
        second_node = None
        while time.monotonic() < deadline:
            try:
                second_node = ray_tpu.get(a.node.remote(), timeout=15)
                break
            except Exception:
                time.sleep(0.3)
        assert second_node is not None and second_node != victim.node_id
    else:
        # actor landed on the survivor; killing the other node must not hurt
        cluster.kill_node(victim)
        assert ray_tpu.get(a.node.remote(), timeout=30) == first_node


def test_pg_replaced_after_node_death(cluster):
    """A PG with a bundle on a dead node is partially re-placed: the lost
    bundle moves to a live node, surviving bundle locations are untouched,
    and new leases against the re-placed bundle succeed (reference:
    GcsPlacementGroupManager reschedules bundles on node death)."""
    # the "pgz" tag pins bundles to THIS test's three nodes (the shared
    # module cluster has live nodes from earlier tests)
    keeper = cluster.add_node(num_cpus=2, resources={"pgz": 2})
    victim = cluster.add_node(num_cpus=2, resources={"pgz": 2})
    spare = cluster.add_node(num_cpus=2, resources={"pgz": 2})
    ray_tpu.init(address=cluster.address)

    pg = ray_tpu.placement_group(
        [{"CPU": 1, "pgz": 1}, {"CPU": 1, "pgz": 1}],
        strategy="STRICT_SPREAD",
    )
    assert pg.wait(20)
    locs = pg.table()["bundle_locations"]
    nodes_used = set(locs.values())
    # kill a node hosting one bundle (pick whichever of the three it is)
    doomed = next(n for n in (keeper, victim, spare) if n.node_id in nodes_used)
    survivor_locs = {i: nid for i, nid in locs.items() if nid != doomed.node_id}
    cluster.kill_node(doomed)

    deadline = time.monotonic() + 60
    table = None
    while time.monotonic() < deadline:
        table = pg.table()
        if (
            table["state"] == "CREATED"
            and doomed.node_id not in set(table["bundle_locations"].values())
            and len(table["bundle_locations"]) == 2
        ):
            break
        time.sleep(0.3)
    assert table is not None and table["state"] == "CREATED"
    new_locs = table["bundle_locations"]
    assert doomed.node_id not in set(new_locs.values())
    # surviving bundle kept its location
    for i, nid in survivor_locs.items():
        assert new_locs[i] == nid

    @ray_tpu.remote
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    for idx in (0, 1):
        node = ray_tpu.get(
            where.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=idx
                )
            ).remote(),
            timeout=60,
        )
        assert node == new_locs[idx]
