"""Unit tests pinning the _NormalTaskSubmitter state machine (worker.py).

The lease cache was previously only covered end-to-end (VERDICT Weak
#10); these tests drive the state machine directly with a fake worker so
each transition is pinned in isolation:

  - chunking ladder (_take_chunk_locked): sub-5ms functions coalesce up
    to the cap, slow/unmeasured functions ride alone, a batch stops at a
    function whose latency profile differs, cancelled specs are consumed
    without entering a chunk;
  - stall detection (_scale_locked): an old in-flight dispatch overrides
    a stale-low EMA and scales the pool immediately (and wide), while
    the un-stalled path ramps exponentially and respects request spacing;
  - dispose / re-register: an empty submitter becomes disposable only
    after the idle window, try_dispose re-verifies emptiness, submit()
    on a disposed submitter refuses (the caller mints a fresh one — the
    janitor-race contract _enqueue_normal_task relies on).
"""

import threading
import time
from collections import deque

import pytest

from ray_tpu.core.task import TaskSpec
from ray_tpu.core.worker import _Lease, _NormalTaskSubmitter
from ray_tpu.utils.config import config


class FakePool:
    """Records submissions instead of running them (the real pool only
    carries _drain_sends/_acquire_lease/_release thunks)."""

    def __init__(self):
        self.jobs = []

    def submit(self, fn, *args):
        self.jobs.append((fn, args))

    def names(self):
        return [fn.__name__ for fn, _ in self.jobs]


class FakeClientPool:
    def __init__(self):
        self.calls = []

    def get(self, addr):
        return self

    def drop(self, addr):
        pass

    def call_oneway(self, method, **kwargs):
        self.calls.append((method, kwargs))


class FakeWorker:
    def __init__(self):
        self._submit_pool = FakePool()
        self._inflight_push = {}
        self._cancelled_tasks = set()
        self._shutdown = threading.Event()
        self.workers = FakeClientPool()
        self.agents = FakeClientPool()
        self.errors = []

    def _store_error_returns(self, spec, err):
        self.errors.append((spec, err))


class _Tid:
    def __init__(self, hexstr):
        self._h = hexstr

    def hex(self):
        return self._h


def spec(name, fn_id="fn", task_hex=None):
    return TaskSpec(
        task_id=_Tid(task_hex or f"t_{name}"),
        fn_id=fn_id, fn_name=name, args_frame=b"", num_returns=1,
        owner_address="owner:0", resources={"CPU": 1.0}, name=name,
    )


@pytest.fixture
def sub():
    w = FakeWorker()
    s = _NormalTaskSubmitter(w, {"CPU": 1.0}, None)
    return w, s


# ---------------------------------------------------------------------------
# chunking ladder
# ---------------------------------------------------------------------------


def test_fast_fns_coalesce_into_one_chunk(sub):
    w, s = sub
    s._fn_lat["fast"] = 0.001  # measured sub-5ms: batchable
    with s.lock:
        s.pending = deque(spec(f"a{i}", fn_id="fast") for i in range(8))
        chunk = s._take_chunk_locked()
    assert [c.fn_name for c in chunk] == [f"a{i}" for i in range(8)]
    assert not s.pending


def test_unmeasured_fn_rides_alone(sub):
    # the 10ms prior is above the 5ms batching gate: a function with no
    # latency history must never execute serially behind batch peers
    w, s = sub
    with s.lock:
        s.pending = deque(spec(f"a{i}", fn_id="new_fn") for i in range(4))
        chunk = s._take_chunk_locked()
    assert len(chunk) == 1
    assert len(s.pending) == 3


def test_slow_fn_rides_alone(sub):
    w, s = sub
    s._fn_lat["slow"] = 0.5
    with s.lock:
        s.pending = deque(spec(f"s{i}", fn_id="slow") for i in range(3))
        chunk = s._take_chunk_locked()
    assert len(chunk) == 1


def test_batch_stops_at_differing_profile(sub):
    # fast, fast, SLOW, fast: the chunk takes the fast prefix and stops —
    # the slow one must not ride (and the trailing fast one stays queued
    # behind it, preserving order)
    w, s = sub
    s._fn_lat["fast"] = 0.001
    s._fn_lat["slow"] = 0.1
    with s.lock:
        s.pending = deque([
            spec("f1", fn_id="fast"), spec("f2", fn_id="fast"),
            spec("s1", fn_id="slow"), spec("f3", fn_id="fast"),
        ])
        chunk = s._take_chunk_locked()
    assert [c.fn_name for c in chunk] == ["f1", "f2"]
    assert [c.fn_name for c in s.pending] == ["s1", "f3"]


def test_chunk_cap_divides_queue_across_idle_leases(sub):
    # 16 queued, 3 more idle leases waiting: the cap (pending // (idle+1))
    # spreads the queue instead of letting one lease swallow it
    w, s = sub
    s._fn_lat["fast"] = 0.001
    with s.lock:
        s.pending = deque(spec(f"a{i}", fn_id="fast") for i in range(16))
        s.idle = [object(), object(), object()]
        chunk = s._take_chunk_locked()
    assert len(chunk) == 4


def test_cancelled_specs_consumed_not_chunked(sub):
    w, s = sub
    s._fn_lat["fast"] = 0.001
    cancelled = spec("dead", fn_id="fast", task_hex="t_dead")
    w._cancelled_tasks.add("t_dead")
    with s.lock:
        s.pending = deque([cancelled, spec("live", fn_id="fast")])
        chunk = s._take_chunk_locked()
    assert [c.fn_name for c in chunk] == ["live"]
    assert len(w.errors) == 1 and w.errors[0][0] is cancelled


# ---------------------------------------------------------------------------
# stall detection / pool sizing
# ---------------------------------------------------------------------------


def test_stall_detection_scales_past_ema(sub):
    # EMA says 10ms, but the oldest in-flight dispatch is 5s old: the
    # pool is provably stuck behind long tasks — scale NOW, one lease per
    # stuck-or-queued task, ignoring the request-spacing timer
    w, s = sub
    with s.lock:
        s.pending = deque(spec(f"q{i}") for i in range(4))
        s.nbusy = 2
        s._dispatch_ts = {"t_old": time.monotonic() - 5.0}
        s._next_request_at = time.monotonic() + 10.0  # spacing must not gate
        s._scale_locked()
    # want = pending + nbusy = 6, minus the 2 held → 4 new acquisitions
    assert s.requesting == 4
    assert s.w._submit_pool.names().count("_acquire_lease") == 4


def test_unstalled_ramp_is_exponential_and_spaced(sub):
    w, s = sub
    with s.lock:
        s.pending = deque(spec(f"q{i}") for i in range(100))
        s.idle = [
            _Lease("agent:0", f"w{i}:0", f"l{i}") for i in range(2)
        ]
        s._svc_latency = 1.0  # 100 tasks * 1s / rampup target >> held
        s._scale_locked()
    # held=2 → at most doubles (want≤4) → need = 4-2-0 = 2 new requests
    assert s.requesting == 2
    with s.lock:
        fired_at = s._next_request_at
        s._scale_locked()  # spacing timer gates an immediate second wave
    assert s.requesting == 2 and fired_at > time.monotonic()


def test_empty_queue_never_scales(sub):
    w, s = sub
    with s.lock:
        s._scale_locked()
    assert s.requesting == 0 and not s.w._submit_pool.jobs


# ---------------------------------------------------------------------------
# dispose / re-register
# ---------------------------------------------------------------------------


def test_maintain_tick_reaps_idle_leases_and_reports_disposable(sub):
    w, s = sub
    old = _Lease("agent:0", "w1:0", "lease1")
    old.idle_since = time.monotonic() - float(config.lease_keepalive_s) - 1
    warm = _Lease("agent:0", "w2:0", "lease2")
    with s.lock:
        s.idle = [old, warm]
    assert s.maintain_tick() is False  # warm lease still held → not empty
    assert ("release_worker", {"lease_id": "lease1", "kill": False}) in (
        w.agents.calls
    )
    with s.lock:
        assert s.idle == [warm]


def test_dispose_requires_empty_past_window(sub):
    w, s = sub
    assert s.maintain_tick() is False  # empty, but the 60s window not up
    s._empty_since = time.monotonic() - 61.0
    assert s.maintain_tick() is True
    # still-queued work blocks disposal even past the window
    with s.lock:
        s.pending.append(spec("late"))
    assert s.try_dispose() is False
    with s.lock:
        s.pending.clear()
    assert s.try_dispose() is True


def test_submit_after_dispose_refuses(sub):
    # the janitor-race contract: a submit that loses to the disposal
    # sweep gets False and _enqueue_normal_task mints a fresh submitter
    w, s = sub
    assert s.try_dispose() is True
    assert s.submit(spec("x")) is False
    with s.lock:
        assert not s.pending  # refused submits must not strand specs


def test_submit_on_live_submitter_plans_and_kicks_sender(sub):
    w, s = sub
    lease = _Lease("agent:0", "w1:0", "lease1")
    with s.lock:
        s.idle = [lease]
    assert s.submit(spec("go")) is True
    # the idle lease was reserved for the spec and the send handed to the
    # pool (sends happen OFF the submit thread so bursts coalesce)
    assert s.nbusy == 1
    assert "_drain_sends" in s.w._submit_pool.names()
