"""WAL-bypass static check (tier-1): the control store's durability
invariant — every state-table mutation flows through the _apply choke
point — must hold for the checked-in source, and the checker itself must
keep catching each bypass pattern."""

import os
import sys
import textwrap

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
sys.path.insert(0, TOOLS)

from check_wal_choke import check_file, check_source  # noqa: E402


def test_control_store_respects_wal_choke_point():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ray_tpu", "core", "control_store.py",
    )
    violations = check_file(path)
    assert not violations, "\n".join(violations)


def _check(body: str):
    return check_source(textwrap.dedent(body))


def test_checker_flags_direct_table_write():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn, ns, key, value):
                self._kv[ns][key] = value
    """)
    assert len(violations) == 1 and "rpc_sneaky" in violations[0]


def test_checker_flags_mutating_method_call():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn, aid):
                self._actors.pop(aid)
    """)
    assert violations and ".pop()" in violations[0]


def test_checker_flags_aliased_record_mutation():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn, aid):
                record = self._actors.get(aid)
                record["state"] = "DEAD"
    """)
    assert len(violations) == 1


def test_checker_flags_loop_alias_mutation():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn):
                for pg in self._pgs.values():
                    pg["state"] = "REMOVED"
    """)
    assert len(violations) == 1


def test_checker_flags_transitive_alias():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn):
                doomed = [a for a in self._actors.values()]
                for rec in doomed:
                    rec["state"] = "DEAD"
    """)
    assert len(violations) == 1


def test_checker_flags_direct_mut_call():
    violations = _check("""
        class ControlStore:
            def rpc_sneaky(self, conn, ns, key, value):
                self._mut_kv_put(ns, key, value)
    """)
    assert violations and "bypasses the WAL choke point" in violations[0]


def test_checker_allows_reads_and_mut_functions():
    violations = _check("""
        class ControlStore:
            def _mut_kv_put(self, ns, key, value):
                self._kv.setdefault(ns, {})[key] = value

            def _apply(self, op, *args):
                return getattr(self, "_mut_" + op)(*args)

            def rpc_kv_get(self, conn, ns, key):
                return self._kv.get(ns, {}).get(key)

            def rpc_list(self, conn):
                return [dict(r) for r in self._actors.values()]

            def rpc_ok(self, conn, ns, key, value):
                return self._apply("kv_put", ns, key, value)
    """)
    assert not violations, violations


def test_checker_honors_copy_opt_out():
    violations = _check("""
        class ControlStore:
            def rpc_fine(self, conn, aid):
                rec = dict(self._actors[aid])
                rec["state"] = "X"  # wal: copy
                return rec
    """)
    assert not violations, violations
