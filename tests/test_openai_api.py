"""OpenAI front door tests (serve/openai/): an openai-client-shaped
suite — completions + chat + SSE streaming against a 4-replica
deployment through the HTTP proxy, session/model affinity, usage
accounting, OpenAI error bodies, and the SSE edge cases (zero-token
completions, stream/unary parity, client disconnect freeing the
engine's KV slot). No real ``openai`` dependency: the requests and the
response-shape assertions mirror what openai-python sends and parses.
"""

import http.client
import json
import socket
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

MODEL = "tiny"
DEPLOYMENT = "openai-llm"


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=8)
    serve.start(http_port=0)
    yield ray_tpu
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def front(rt):
    """4-replica OpenAI deployment + the proxy address serving it."""
    from ray_tpu.serve import llm as serve_llm

    handle = serve_llm.deploy(
        {MODEL: serve_llm.LLMConfig(model_id="gpt2-tiny", max_batch_size=4)},
        name=DEPLOYMENT, num_replicas=4, route_prefix="/v1",
    )
    deadline = time.monotonic() + 60
    addrs = []
    while time.monotonic() < deadline and not addrs:
        addrs = serve.proxy_addresses()
        time.sleep(0.2)
    assert addrs, "no HTTP proxy came up"
    yield addrs[0], handle
    serve.delete(DEPLOYMENT)


def _post(addr, path, body, timeout=180):
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _sse_events(raw: bytes):
    """Parse an SSE byte stream into its data payloads, asserting the
    exact framing: every event is one ``data: {...}\\n\\n`` block."""
    text = raw.decode()
    blocks = [b for b in text.split("\n\n") if b.strip()]
    events = []
    for b in blocks:
        assert b.startswith("data: "), f"bad SSE framing: {b!r}"
        events.append(b[len("data: "):])
    return events


def _stream(addr, path, body, timeout=180, read_events=None):
    """POST with stream=true over http.client; returns (status, ctype,
    sse payload strings)."""
    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, resp.getheader("Content-Type"), _sse_events(raw)
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# the openai-client-shaped pass: completions + chat + streaming, 4 replicas
# ---------------------------------------------------------------------------


def test_models_endpoint(front):
    addr, _ = front
    with urllib.request.urlopen(f"http://{addr}/v1/models", timeout=30) as r:
        body = json.loads(r.read())
    assert body["object"] == "list"
    assert [m["id"] for m in body["data"]] == [MODEL]
    assert body["data"][0]["object"] == "model"


def test_completion_shape_and_usage(front):
    addr, _ = front
    st, body = _post(addr, "/v1/completions", {
        "model": MODEL, "prompt": "hello world", "max_tokens": 6,
        "temperature": 0, "user": "alice",
    })
    assert st == 200
    assert body["id"].startswith("cmpl-")
    assert body["object"] == "text_completion"
    assert body["model"] == MODEL
    choice = body["choices"][0]
    assert choice["index"] == 0 and isinstance(choice["text"], str)
    assert choice["finish_reason"] == "length"
    usage = body["usage"]
    assert usage["prompt_tokens"] == len("hello world".encode())
    assert usage["completion_tokens"] == 6
    assert usage["total_tokens"] == usage["prompt_tokens"] + 6
    assert body["system_fingerprint"].startswith("rt-replica-")


def test_chat_completion_shape(front):
    addr, _ = front
    st, body = _post(addr, "/v1/chat/completions", {
        "model": MODEL, "max_tokens": 5, "temperature": 0, "user": "alice",
        "messages": [
            {"role": "system", "content": "you are terse"},
            {"role": "user", "content": "hi"},
        ],
    })
    assert st == 200
    assert body["id"].startswith("chatcmpl-")
    assert body["object"] == "chat.completion"
    msg = body["choices"][0]["message"]
    assert msg["role"] == "assistant" and isinstance(msg["content"], str)
    assert body["choices"][0]["finish_reason"] == "length"
    assert body["usage"]["completion_tokens"] == 5


def test_stream_unary_parity_same_prompt(front):
    """stream=false and stream=true on the same greedy prompt decode the
    same text (pinned to one replica by the session key, so both hit the
    same engine deterministically)."""
    addr, _ = front
    req = {"model": MODEL, "prompt": "abcabc", "max_tokens": 8,
           "temperature": 0, "user": "alice"}
    st, unary = _post(addr, "/v1/completions", req)
    assert st == 200
    unary_text = unary["choices"][0]["text"]

    st, ctype, events = _stream(addr, "/v1/completions",
                                {**req, "stream": True})
    assert st == 200 and ctype == "text/event-stream"
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    streamed = "".join(c["choices"][0]["text"] for c in chunks)
    assert streamed == unary_text, (streamed, unary_text)
    # exactly one chunk carries the finish_reason, and it is the last
    finals = [c for c in chunks if c["choices"][0]["finish_reason"]]
    assert len(finals) == 1 and finals[0] is chunks[-1]
    assert finals[0]["usage"]["completion_tokens"] == 8


def test_chat_streaming_role_then_deltas(front):
    addr, _ = front
    st, ctype, events = _stream(addr, "/v1/chat/completions", {
        "model": MODEL, "max_tokens": 4, "temperature": 0, "user": "alice",
        "stream": True,
        "messages": [{"role": "user", "content": "hey"}],
    })
    assert st == 200 and ctype == "text/event-stream"
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    # first chunk announces the assistant role, middles carry content,
    # the final chunk has the finish_reason and usage
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"
    assert chunks[-1]["usage"]["completion_tokens"] == 4
    content = "".join(
        c["choices"][0]["delta"].get("content", "") for c in chunks
    )
    assert isinstance(content, str)


def test_zero_token_completion_unary_and_stream(front):
    addr, _ = front
    req = {"model": MODEL, "prompt": "xyz", "max_tokens": 0,
           "temperature": 0, "user": "alice"}
    st, body = _post(addr, "/v1/completions", req)
    assert st == 200
    assert body["choices"][0]["text"] == ""
    assert body["choices"][0]["finish_reason"] == "length"
    assert body["usage"]["completion_tokens"] == 0

    st, _ctype, events = _stream(addr, "/v1/completions",
                                 {**req, "stream": True})
    assert st == 200 and events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    # no content chunks — only the finish_reason chunk
    assert all(c["choices"][0]["text"] == "" for c in chunks)
    assert chunks[-1]["usage"]["completion_tokens"] == 0


def test_session_affinity_pins_one_replica(front):
    """The 4-replica affinity criterion: every request with one session
    key lands on the SAME replica (rendezvous pin → warm KV slots),
    while distinct sessions spread across replicas."""
    addr, _ = front
    fingerprints = set()
    for _ in range(6):
        st, body = _post(addr, "/v1/completions", {
            "model": MODEL, "prompt": "pin me", "max_tokens": 1,
            "temperature": 0, "user": "alice",
        })
        assert st == 200
        fingerprints.add(body["system_fingerprint"])
    assert len(fingerprints) == 1, fingerprints

    spread = set()
    for i in range(8):
        st, body = _post(addr, "/v1/completions", {
            "model": MODEL, "prompt": "spread", "max_tokens": 0,
            "temperature": 0, "user": f"user-{i}",
        })
        assert st == 200
        spread.add(body["system_fingerprint"])
    # 8 independent sessions over 4 replicas: all landing on one replica
    # would mean the session key is ignored (P ≈ 6e-5 by chance)
    assert len(spread) >= 2, spread


def test_openai_error_bodies(front):
    addr, _ = front
    st, body = _post(addr, "/v1/completions", {"model": MODEL})
    assert st == 400
    err = body["error"]
    assert err["type"] == "invalid_request_error"
    assert err["param"] == "prompt" and err["code"] == "missing_field"

    st, body = _post(addr, "/v1/completions",
                     {"model": "no-such-model", "prompt": "x"})
    assert st == 404
    assert body["error"]["code"] == "model_not_found"

    st, body = _post(addr, "/v1/chat/completions",
                     {"model": MODEL, "messages": []})
    assert st == 400 and body["error"]["param"] == "messages"


def test_stream_error_rides_sse(front):
    """A stream=true request that fails validation still answers on the
    SSE channel (the proxy committed to streaming from the body probe)."""
    addr, _ = front
    st, ctype, events = _stream(addr, "/v1/completions", {
        "model": "no-such-model", "prompt": "x", "stream": True,
    })
    assert st == 200 and ctype == "text/event-stream"
    assert events[-1] == "[DONE]"
    err = json.loads(events[0])["error"]
    assert err["code"] == "model_not_found"


def test_client_disconnect_mid_stream_keeps_serving(front):
    """Abruptly closing the socket mid-SSE must not wedge the proxy or
    the replica: the stream generator is closed (cancelling the replica
    task), the engine drains back to zero occupied KV slots, and the
    same session keeps serving."""
    addr, handle = front
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=60)
    body = json.dumps({
        "model": MODEL, "prompt": "disconnect", "max_tokens": 100,
        "temperature": 0, "user": "alice", "stream": True,
    }).encode()
    sock.sendall(
        b"POST /v1/completions HTTP/1.1\r\nHost: x\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
    )
    got = b""
    while b"data: " not in got:  # first SSE event arrived
        chunk = sock.recv(4096)
        assert chunk, "stream ended before first event"
        got += chunk
    sock.close()  # mid-stream disconnect

    # the engine drains its slot (alice's replica is the only one holding
    # the model, so the model-affinity handle reaches exactly it)
    stats_handle = handle.options(multiplexed_model_id=MODEL)
    deadline = time.monotonic() + 60
    occupied = None
    while time.monotonic() < deadline:
        stats = stats_handle.remote(
            None, method="engine_stats"
        ).result(timeout_s=60)
        occupied = stats.get("occupied")
        if occupied == 0:
            break
        time.sleep(0.3)
    assert occupied == 0, stats

    # and the front door still serves the same session
    st, body = _post(addr, "/v1/completions", {
        "model": MODEL, "prompt": "still alive", "max_tokens": 2,
        "temperature": 0, "user": "alice",
    })
    assert st == 200 and len(body["choices"][0]["text"]) >= 0


# ---------------------------------------------------------------------------
# engine-level: closing the token stream frees the KV slot mid-decode
# ---------------------------------------------------------------------------


def test_engine_stream_close_frees_kv_slot(monkeypatch):
    """Unit-level pin of the cancellation chain: closing _stream_tokens
    marks the request cancelled and the engine reaps its slot at the
    next round instead of decoding to max_new for nobody. The decode
    step is throttled so cancellation provably lands mid-generation."""
    from ray_tpu.models import gpt2_decode
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    real_multi = gpt2_decode.decode_multi
    real_single = gpt2_decode.decode_and_sample

    def slow_multi(*a, **kw):
        time.sleep(0.05)
        return real_multi(*a, **kw)

    def slow_single(*a, **kw):
        time.sleep(0.05)
        return real_single(*a, **kw)

    monkeypatch.setattr(gpt2_decode, "decode_multi", slow_multi)
    monkeypatch.setattr(gpt2_decode, "decode_and_sample", slow_single)

    server = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=2))
    try:
        gen = server({"prompt_tokens": [1, 2, 3], "max_new_tokens": 120,
                      "temperature": 0.0, "stream": True})
        seen = [next(gen) for _ in range(3)]
        assert [s["index"] for s in seen] == [0, 1, 2]
        rounds_at_close = server.batch_stats()["batches"]
        gen.close()  # client went away

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if server.batch_stats()["occupied"] == 0:
                break
            time.sleep(0.05)
        stats = server.batch_stats()
        assert stats["occupied"] == 0, stats
        # the engine must NOT have decoded anywhere near the 120-token
        # budget after the close (15+ throttled rounds); a couple of
        # in-flight rounds are allowed
        assert stats["batches"] - rounds_at_close <= 4, (
            stats, rounds_at_close
        )
    finally:
        server.unload()


def test_engine_unload_fails_inflight_requests(monkeypatch):
    """Evicting an engine (multiplex LRU) must FAIL in-flight streams
    immediately — not strand their consumers until the 300s timeout."""
    from ray_tpu.models import gpt2_decode
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    real_multi = gpt2_decode.decode_multi
    real_single = gpt2_decode.decode_and_sample
    monkeypatch.setattr(
        gpt2_decode, "decode_multi",
        lambda *a, **kw: (time.sleep(0.05), real_multi(*a, **kw))[1],
    )
    monkeypatch.setattr(
        gpt2_decode, "decode_and_sample",
        lambda *a, **kw: (time.sleep(0.05), real_single(*a, **kw))[1],
    )
    server = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=2))
    gen = server({"prompt_tokens": [1, 2], "max_new_tokens": 120,
                  "temperature": 0.0, "stream": True})
    next(gen)  # request admitted into a KV slot
    t0 = time.monotonic()
    server.unload()
    with pytest.raises(RuntimeError, match="unloaded"):
        for _ in gen:
            pass
    assert time.monotonic() - t0 < 10


def test_engine_unload_releases_prefix_block_pool():
    """Multiplex eviction must not leak the prefix pool: after unload()
    the pool is closed (0 resident blocks, unregistered) even when the
    evicted engine still had cached blocks parked."""
    from ray_tpu.serve import prefix_cache
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    server = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=2))
    prompt = list(range(100))
    server({"prompt_tokens": prompt, "max_new_tokens": 2,
            "temperature": 0.0})
    pool = server._prefix_pool
    assert pool.resident() > 0
    assert pool in prefix_cache.live_pools()
    server.unload()
    assert pool.resident() == 0
    assert pool not in prefix_cache.live_pools()


# ---------------------------------------------------------------------------
# tokenizer + protocol units
# ---------------------------------------------------------------------------


def test_byte_tokenizer_roundtrip_and_incremental():
    from ray_tpu.serve.openai.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    for text in ("hello", "héllo wörld", "日本語", ""):
        assert tok.decode(tok.encode(text)) == text
    # incremental decoding never splits a multibyte character
    dec = tok.incremental_decoder()
    out = "".join(dec.feed(t) for t in tok.encode("héllo")) + dec.flush()
    assert out == "héllo"


def test_chat_template_flattens_roles():
    from ray_tpu.serve.openai.protocol import ChatMessage
    from ray_tpu.serve.openai.tokenizer import ByteTokenizer, render_chat

    msgs = [ChatMessage("system", "be brief"), ChatMessage("user", "hi")]
    flat = render_chat(msgs)
    assert flat.index("be brief") < flat.index("hi")
    assert flat.endswith("<|assistant|>")
    assert ByteTokenizer().decode(ByteTokenizer().encode(flat)) == flat


def test_request_validation():
    from ray_tpu.serve.openai.protocol import (
        ChatCompletionRequest,
        CompletionRequest,
        OpenAIError,
    )

    r = CompletionRequest.from_body(
        {"model": "m", "prompt": ["one"], "max_tokens": 3}
    )
    assert r.prompt == "one" and r.max_tokens == 3
    with pytest.raises(OpenAIError):
        CompletionRequest.from_body({"prompt": "x"})  # missing model
    with pytest.raises(OpenAIError):
        CompletionRequest.from_body(
            {"model": "m", "prompt": "x", "temperature": 9}
        )
    r = ChatCompletionRequest.from_body({
        "model": "m", "max_completion_tokens": 7,
        "messages": [{"role": "user", "content": "x"}],
    })
    assert r.max_tokens == 7
