"""Peer-to-peer ring collective tests: correctness vs an exact local
reference, the head-traffic guarantee (control-store KV bytes are
rendezvous-only, independent of payload size), quantized-allreduce
numerics bounds + wire-byte reduction, transport routing for send/recv,
the RT_COLLECTIVE_P2P kill switch, peer-death failure surfacing with
group re-init, and a chaos leg under injected connection drops."""

import time

import numpy as np
import pytest

import ray_tpu

WORLD = 4
# deterministic per-rank inputs so the driver can compute the exact
# reference without moving data
SEED = 1234


def _rank_input(rank, n, dtype, seed=SEED):
    rng = np.random.default_rng(seed + rank)
    return rng.uniform(-1.0, 1.0, n).astype(dtype)


def _exact(n, dtype, world=WORLD, op="sum", seed=SEED):
    xs = [_rank_input(r, n, dtype, seed).astype(np.float64)
          for r in range(world)]
    if op == "sum":
        out = np.sum(xs, axis=0)
    elif op == "min":
        out = np.min(xs, axis=0)
    elif op == "max":
        out = np.max(xs, axis=0)
    else:
        out = np.prod(xs, axis=0)
    return out


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=8)
    yield ray_tpu
    ray_tpu.shutdown()


def _head_kv_stats():
    from ray_tpu.core import worker as worker_mod

    return worker_mod.global_worker().control.call("kv_stats")


def _head_kv_bytes():
    s = _head_kv_stats()
    return s["bytes_put"] + s["bytes_out"]


@ray_tpu.remote
class Rank:
    def __init__(self, rank, world):
        self.rank, self.world = rank, world

    def setup(self, group):
        from ray_tpu import collective

        collective.init_collective_group(self.world, self.rank, "cpu", group)
        return True

    def destroy(self, group):
        from ray_tpu import collective

        collective.destroy_collective_group(group)
        return True

    def set_flag(self, name, value):
        from ray_tpu.utils.config import config

        config.set(name, value)
        return True

    def reset_stats(self):
        from ray_tpu.collective import p2p

        return p2p.reset_stats()

    def stats(self):
        from ray_tpu.collective import p2p

        return p2p.snapshot_stats()

    def metric_snapshot(self):
        from ray_tpu.observability import core_metrics

        return {
            "bytes": core_metrics.collective_bytes_sent.snapshot(),
            "latency": core_metrics.collective_op_latency_s.snapshot(),
        }

    def allreduce(self, group, n, dtype="float32", op="sum", quant=None,
                  timeout_s=None, seed=SEED):
        from ray_tpu import collective

        x = _rank_input(self.rank, n, dtype, seed)
        return collective.allreduce(x, op=op, group_name=group,
                                    quant=quant, timeout_s=timeout_s)

    def allreduce_catch(self, group, n, timeout_s, **kw):
        """allreduce that reports failures instead of raising (peer-death
        test: survivors must ERROR, not hang)."""
        from ray_tpu import collective
        from ray_tpu.core.exceptions import CollectiveError

        t0 = time.monotonic()
        try:
            self.allreduce(group, n, timeout_s=timeout_s, **kw)
            return ("ok", time.monotonic() - t0)
        except (CollectiveError, TimeoutError) as e:
            return ("err", type(e).__name__, str(e)[:200],
                    time.monotonic() - t0)

    def reducescatter(self, group, shape, dtype="float32", op="sum",
                      seed=SEED):
        from ray_tpu import collective

        n = int(np.prod(shape))
        x = _rank_input(self.rank, n, dtype, seed).reshape(shape)
        return collective.reducescatter(x, op=op, group_name=group)

    def allgather(self, group, n_mine):
        from ray_tpu import collective

        x = np.full(n_mine, float(self.rank), dtype=np.float32)
        return [np.asarray(a) for a in
                collective.allgather(x, group_name=group)]

    def broadcast(self, group, src, n):
        from ray_tpu import collective

        x = (_rank_input(src, n, "float32") if self.rank == src
             else np.zeros(1, dtype=np.float32))
        return collective.broadcast(x, src_rank=src, group_name=group)

    def send(self, group, dst, n, seed=SEED):
        from ray_tpu import collective

        collective.send(_rank_input(self.rank, n, "float32", seed), dst,
                        group_name=group)
        return True

    def recv(self, group, src, timeout_s=60.0):
        from ray_tpu import collective

        return np.asarray(collective.recv(src, group_name=group,
                                          timeout_s=timeout_s))

    def quant_validation_errors(self, group):
        """Exercise quant parameter validation inside the rank process."""
        from ray_tpu.collective import p2p

        g = p2p.group_for(group)
        errs = []
        for kwargs in (
            {"op": "min", "quant": "int8"},
            {"op": "sum", "quant": "int4"},
        ):
            try:
                p2p.ring_allreduce(g, np.ones(4, np.float32),
                                   kwargs["op"], "vtag",
                                   quant=kwargs["quant"])
                errs.append(None)
            except ValueError as e:
                errs.append(str(e)[:60])
        try:
            p2p.ring_allreduce(g, np.ones(4, np.int32), "sum", "vtag2",
                               quant="int8")
            errs.append(None)
        except ValueError as e:
            errs.append(str(e)[:60])
        return errs

    def raw_p2p_send(self, group, dst, n):
        """Drive the ring transport directly (stale-incarnation test)."""
        from ray_tpu.collective import p2p
        from ray_tpu.core.exceptions import CollectiveError

        g = p2p.group_for(group)
        try:
            p2p.p2p_send(g, dst, "stale-probe",
                         np.zeros(n, np.float32), timeout_s=8.0)
            return "ok"
        except CollectiveError as e:
            return ("err", str(e)[:160])

    def arm_death_at_step(self, step_no):
        """Kill this process the moment its NEXT ring op reaches reduce-
        scatter step ``step_no`` — deterministic mid-ring death."""
        import os

        from ray_tpu.collective import p2p

        def hook(phase, step):
            if phase == "rs" and step >= step_no:
                os._exit(1)

        p2p._step_hook = hook
        return True


def _make_group(rt, world, group, cls=Rank):
    members = [cls.remote(i, world) for i in range(world)]
    rt.get([m.setup.remote(group) for m in members], timeout=60)
    return members


# ---------------------------------------------------------------------------
# correctness + wire accounting
# ---------------------------------------------------------------------------


def test_p2p_allreduce_matches_exact_and_wire_bytes(rt):
    members = _make_group(rt, WORLD, "p2p_ar")
    n = 65536  # 256 KiB f32 — well above the p2p floor
    rt.get([m.reset_stats.remote() for m in members], timeout=30)
    head0 = _head_kv_bytes()
    for op in ("sum", "min", "max"):
        outs = rt.get(
            [m.allreduce.remote("p2p_ar", n, op=op) for m in members],
            timeout=120,
        )
        exact = _exact(n, "float32", op=op)
        for out in outs:
            assert out.dtype == np.float32 and out.shape == (n,)
            np.testing.assert_allclose(out, exact, rtol=1e-5, atol=1e-5)
            np.testing.assert_array_equal(out, outs[0])
    # every byte rode the ring: ring allreduce moves exactly
    # 2*(world-1)*(n/world) elements per rank per op, and the head saw
    # NO collective payload traffic at all
    stats = rt.get([m.stats.remote() for m in members], timeout=30)
    expect = 3 * 2 * (WORLD - 1) * (n // WORLD) * 4
    for s in stats:
        assert s["bytes_sent"] == expect, s
        assert s["bytes_recv"] == expect, s
    assert _head_kv_bytes() == head0


def test_head_traffic_independent_of_payload_size(rt):
    members = _make_group(rt, WORLD, "p2p_head")
    deltas = []
    for n in (65536, 262144):  # 256 KiB vs 1 MiB
        before = _head_kv_bytes()
        rt.get([m.allreduce.remote("p2p_head", n) for m in members],
               timeout=120)
        deltas.append(_head_kv_bytes() - before)
    # rendezvous happened at init; the ops themselves are head-free —
    # 4x the payload moves zero extra bytes through the control store
    assert deltas == [0, 0]


def test_reducescatter_allgather_broadcast_p2p(rt):
    members = _make_group(rt, WORLD, "p2p_ops")
    rt.get([m.reset_stats.remote() for m in members], timeout=30)
    head0 = _head_kv_bytes()

    # reducescatter: (8, 8192) f32 = 256 KiB, rank r gets rows 2r..2r+2
    shape = (8, 8192)
    outs = rt.get(
        [m.reducescatter.remote("p2p_ops", shape) for m in members],
        timeout=120,
    )
    exact = _exact(int(np.prod(shape)), "float32").reshape(shape)
    rows = shape[0] // WORLD
    for r, out in enumerate(outs):
        assert out.shape == (rows, shape[1])
        np.testing.assert_allclose(
            out, exact[r * rows:(r + 1) * rows], rtol=1e-5, atol=1e-5
        )

    # allgather with DIFFERENT per-rank sizes (the KV path required
    # nothing here either, but size-divergent routing must not hang)
    gathered = rt.get(
        [m.allgather.remote("p2p_ops", 1000 * (i + 1))
         for i, m in enumerate(members)],
        timeout=120,
    )
    for g in gathered:
        assert [a.size for a in g] == [1000, 2000, 3000, 4000]
        for r, a in enumerate(g):
            np.testing.assert_array_equal(a, np.full(1000 * (r + 1),
                                                     float(r)))

    # broadcast 256 KiB from a non-zero source
    src, n = 1, 65536
    outs = rt.get(
        [m.broadcast.remote("p2p_ops", src, n) for m in members],
        timeout=120,
    )
    ref = _rank_input(src, n, "float32")
    for out in outs:
        np.testing.assert_array_equal(np.asarray(out).reshape(-1), ref)

    stats = rt.get([m.stats.remote() for m in members], timeout=30)
    assert all(s["bytes_sent"] > 0 for s in stats)
    assert _head_kv_bytes() == head0


# ---------------------------------------------------------------------------
# quantized allreduce
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,extra_tol", [
    ("float32", 0.0),
    ("float16", 0.02),   # input representation + final f16 rounding
    ("float64", 0.0),    # accumulation is f32 by design
])
def test_quantized_allreduce_error_bound(rt, dtype, extra_tol):
    members = _make_group(rt, WORLD, f"p2p_q_{dtype}")
    n = 32768 + 7  # non-divisible: exercises ring padding
    outs = rt.get(
        [m.allreduce.remote(f"p2p_q_{dtype}", n, dtype=dtype,
                            quant="int8") for m in members],
        timeout=120,
    )
    exact = _exact(n, dtype)
    # per-element bound: each reduce-scatter hop requantizes a partial
    # sum of k rank contributions (|x| <= 1), error <= k/254 per hop;
    # the allgather quantizes each final chunk once more. For world=4
    # that sums to ~0.05; assert the generous closed form w^2/127.
    bound = (WORLD * WORLD) / 127.0 + extra_tol
    for out in outs:
        assert out.dtype == np.dtype(dtype)
        err = np.abs(out.astype(np.float64) - exact)
        assert err.max() <= bound, (dtype, err.max(), bound)
        # and the quantization is actually useful, not garbage
        assert np.sqrt((err ** 2).mean()) < 0.05
    # allreduce contract: IDENTICAL result on every rank (each chunk's
    # owner adopts the same quantization loss it ships, so data-parallel
    # replicas cannot diverge)
    for out in outs[1:]:
        np.testing.assert_array_equal(out, outs[0])


def test_quantized_allreduce_wire_bytes_reduction(rt):
    members = _make_group(rt, WORLD, "p2p_qwire")
    n = 262144  # 1 MiB f32
    rt.get([m.reset_stats.remote() for m in members], timeout=30)
    rt.get([m.allreduce.remote("p2p_qwire", n) for m in members],
           timeout=120)
    f32_bytes = sum(
        s["bytes_sent"]
        for s in rt.get([m.reset_stats.remote() for m in members],
                        timeout=30)
    )
    rt.get([m.allreduce.remote("p2p_qwire", n, quant="int8")
            for m in members], timeout=120)
    q_bytes = sum(
        s["bytes_sent"]
        for s in rt.get([m.stats.remote() for m in members], timeout=30)
    )
    assert f32_bytes > 0 and q_bytes > 0
    # int8 payload + one f32 scale per 2048-element block ≈ 3.99x fewer
    # wire bytes than f32; the acceptance bar is ≥2x
    assert f32_bytes / q_bytes >= 2.0, (f32_bytes, q_bytes)
    assert f32_bytes / q_bytes > 3.5, (f32_bytes, q_bytes)


def test_quant_parameter_validation(rt):
    members = _make_group(rt, 2, "p2p_qval")
    errs = rt.get(members[0].quant_validation_errors.remote("p2p_qval"),
                  timeout=30)
    assert len(errs) == 3 and all(e is not None for e in errs), errs


def test_quant_roundtrip_unit():
    """Blockwise int8 codec: bounded error, exact zeros, padding tails."""
    from ray_tpu.collective import p2p

    rng = np.random.default_rng(7)
    for n in (1, 100, 2048, 2048 * 3 + 5):
        x = rng.uniform(-3.0, 3.0, n).astype(np.float32)
        block, q, scales = p2p._quant_int8(x)
        assert q.dtype == np.int8 and scales.dtype == np.float32
        back = p2p._dequant_int8(block, q, scales)
        assert back.shape == x.shape
        # half-ulp of the blockwise scale
        per_block_bound = np.repeat(scales, block)[:n] / 2.0 + 1e-7
        assert (np.abs(back - x) <= per_block_bound).all()
    z = np.zeros(100, np.float32)
    block, q, scales = p2p._quant_int8(z)
    np.testing.assert_array_equal(p2p._dequant_int8(block, q, scales), z)


# ---------------------------------------------------------------------------
# send/recv routing
# ---------------------------------------------------------------------------


def test_send_recv_routes_by_size(rt):
    members = _make_group(rt, 2, "p2p_sr")
    a, b = members
    rt.get([m.reset_stats.remote() for m in members], timeout=30)

    # large payload (256 KiB): rides p2p, head sees nothing
    head0 = _head_kv_bytes()
    n_big = 65536
    s = a.send.remote("p2p_sr", 1, n_big, seed=11)
    got = rt.get(b.recv.remote("p2p_sr", 0), timeout=60)
    rt.get(s, timeout=30)
    np.testing.assert_array_equal(got, _rank_input(0, n_big, "float32",
                                                   11))
    assert _head_kv_bytes() == head0
    assert rt.get(b.stats.remote(), timeout=30)["bytes_recv"] == n_big * 4

    # small payload (512 B): rides KV — the receiver's dual wait picks
    # it up off the kv_wait leg
    n_small = 128
    s = a.send.remote("p2p_sr", 1, n_small, seed=12)
    got = rt.get(b.recv.remote("p2p_sr", 0), timeout=60)
    rt.get(s, timeout=30)
    np.testing.assert_array_equal(got, _rank_input(0, n_small, "float32",
                                                   12))
    assert _head_kv_bytes() - head0 >= n_small * 4
    # p2p counters did not move for the small send
    assert rt.get(b.stats.remote(), timeout=30)["bytes_recv"] == n_big * 4

    # interleaved small-then-big to the same receiver stays ordered
    s1 = a.send.remote("p2p_sr", 1, n_small, seed=13)
    rt.get(s1, timeout=30)
    s2 = a.send.remote("p2p_sr", 1, n_big, seed=14)
    got1 = rt.get(b.recv.remote("p2p_sr", 0), timeout=60)
    got2 = rt.get(b.recv.remote("p2p_sr", 0), timeout=60)
    rt.get(s2, timeout=30)
    np.testing.assert_array_equal(
        got1, _rank_input(0, n_small, "float32", 13))
    np.testing.assert_array_equal(
        got2, _rank_input(0, n_big, "float32", 14))


# ---------------------------------------------------------------------------
# kill switch + tiny-payload fallback
# ---------------------------------------------------------------------------


def test_kill_switch_falls_back_to_kv(rt):
    members = [Rank.remote(i, 2) for i in range(2)]
    rt.get([m.set_flag.remote("collective_p2p", False) for m in members],
           timeout=30)
    rt.get([m.setup.remote("p2p_off") for m in members], timeout=60)
    rt.get([m.reset_stats.remote() for m in members], timeout=30)
    head0 = _head_kv_bytes()
    n = 65536
    outs = rt.get([m.allreduce.remote("p2p_off", n) for m in members],
                  timeout=120)
    exact = _exact(n, "float32", world=2)
    for out in outs:
        np.testing.assert_allclose(out, exact, rtol=1e-5, atol=1e-5)
    # everything moved through the head, nothing through the ring
    stats = rt.get([m.stats.remote() for m in members], timeout=30)
    assert all(s["bytes_sent"] == 0 and s["bytes_recv"] == 0
               for s in stats)
    assert _head_kv_bytes() - head0 >= 2 * n * 4
    # restore: worker processes can outlive the actor (pool reuse)
    rt.get([m.set_flag.remote("collective_p2p", True) for m in members],
           timeout=30)


def test_tiny_payload_rides_kv_even_with_p2p(rt):
    members = _make_group(rt, 2, "p2p_tiny")
    rt.get([m.reset_stats.remote() for m in members], timeout=30)
    head0 = _head_kv_bytes()
    outs = rt.get([m.allreduce.remote("p2p_tiny", 16) for m in members],
                  timeout=60)
    exact = _exact(16, "float32", world=2)
    for out in outs:
        np.testing.assert_allclose(out, exact, rtol=1e-6, atol=1e-6)
    stats = rt.get([m.stats.remote() for m in members], timeout=30)
    assert all(s["bytes_sent"] == 0 for s in stats)  # below the floor
    assert _head_kv_bytes() > head0


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_collective_metrics_recorded(rt):
    members = _make_group(rt, 2, "p2p_metrics")
    outs = rt.get(
        [m.allreduce.remote("p2p_metrics", 65536) for m in members],
        timeout=60,
    )
    # doubles as the 2-rank ring correctness check (1-step phases)
    exact = _exact(65536, "float32", world=2)
    for out in outs:
        np.testing.assert_allclose(out, exact, rtol=1e-5, atol=1e-5)
    snap = rt.get(members[0].metric_snapshot.remote(), timeout=30)
    # series keys are tag-value tuples ordered per tag_keys
    assert snap["bytes"]["series"].get(("allreduce", "p2p"), 0) > 0, snap
    lat = snap["latency"]["series"].get(("allreduce",))
    assert lat is not None and lat["count"] >= 1, snap


# ---------------------------------------------------------------------------
# failure paths
# ---------------------------------------------------------------------------


def test_peer_death_surfaces_error_and_group_reinits(rt):
    members = _make_group(rt, WORLD, "p2p_death")
    victim = members[2]
    survivors = [m for i, m in enumerate(members) if i != 2]
    # fast redial budget so the dead peer surfaces quickly (each retry
    # to a closed port otherwise burns the full 10s connect budget)
    rt.get([m.set_flag.remote("rpc_connect_timeout_s", 2.0)
            for m in survivors], timeout=30)
    # the victim enters the op and dies deterministically MID-ring, at
    # reduce-scatter step 1 (step 0's chunks already exchanged)
    rt.get(victim.arm_death_at_step.remote(1), timeout=30)
    victim.allreduce_catch.remote("p2p_death", 262144, 30.0)
    t0 = time.monotonic()
    results = rt.get(
        [m.allreduce_catch.remote("p2p_death", 262144, 30.0)
         for m in survivors],
        timeout=120,
    )
    wall = time.monotonic() - t0
    # every survivor ERRORS (CollectiveError via poison or deadline) —
    # nobody hangs past the op deadline
    assert all(r[0] == "err" for r in results), results
    assert wall < 90, wall
    rt.get([m.set_flag.remote("rpc_connect_timeout_s", 10.0)
            for m in survivors], timeout=30)

    # re-init after failure: survivors destroy, a replacement rank 2
    # joins, the SAME group name works again
    rt.get([m.destroy.remote("p2p_death") for m in survivors], timeout=30)
    replacement = Rank.remote(2, WORLD)
    regroup = survivors[:2] + [replacement] + survivors[2:]
    rt.get([m.setup.remote("p2p_death") for m in regroup], timeout=60)
    outs = rt.get(
        [m.allreduce.remote("p2p_death", 65536) for m in regroup],
        timeout=120,
    )
    exact = _exact(65536, "float32")
    for out in outs:
        np.testing.assert_allclose(out, exact, rtol=1e-5, atol=1e-5)


def test_send_to_destroyed_incarnation_fails_fast(rt):
    """A delivery the receiver bounces (group destroyed/re-initialized,
    token mismatch) must surface as CollectiveError on the SENDER, not
    be silently swallowed as a clean ack."""
    members = _make_group(rt, 2, "p2p_stale")
    rt.get(members[1].destroy.remote("p2p_stale"), timeout=30)
    res = rt.get(members[0].raw_p2p_send.remote("p2p_stale", 1, 16384),
                 timeout=60)
    assert res[0] == "err" and "dropped" in res[1], res


def test_chaos_allreduce_under_connection_drops(rt):
    """4-rank allreduce with 5% injected request/response drops on the
    ring delivery RPC: idempotent tagged delivery + the reap retry
    ladder must still converge to exact results."""
    members = _make_group(rt, WORLD, "p2p_chaos")
    rt.get(
        [m.set_flag.remote("testing_rpc_failure", "coll_deliver:0.05:0.05")
         for m in members],
        timeout=30,
    )
    try:
        for seed in (21, 22, 23):
            outs = rt.get(
                [m.allreduce.remote("p2p_chaos", 65536, seed=seed)
                 for m in members],
                timeout=180,
            )
            exact = _exact(65536, "float32", seed=seed)
            for out in outs:
                np.testing.assert_allclose(out, exact, rtol=1e-5,
                                           atol=1e-5)
    finally:
        rt.get([m.set_flag.remote("testing_rpc_failure", "")
                for m in members], timeout=30)
