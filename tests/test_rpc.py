import threading
import time

import numpy as np
import pytest

from ray_tpu.utils import serialization
from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import (
    ClientPool,
    RemoteError,
    RpcClient,
    RpcConnectionError,
    RpcServer,
)


@pytest.fixture
def server():
    s = RpcServer("test")
    s.register("echo", lambda conn, x: x)
    s.register("add", lambda conn, a, b: a + b)
    s.register("boom", lambda conn: 1 / 0)
    s.register("slow", lambda conn, t: time.sleep(t))
    s.start()
    yield s
    s.stop()


def test_basic_call(server):
    c = RpcClient(server.address)
    assert c.call("add", 2, 3) == 5
    assert c.call("echo", {"k": [1, 2]}) == {"k": [1, 2]}
    c.close()


def test_remote_exception(server):
    c = RpcClient(server.address)
    with pytest.raises(RemoteError, match="ZeroDivisionError"):
        c.call("boom")
    c.close()


def test_concurrent_calls(server):
    c = RpcClient(server.address)
    results = {}

    def worker(i):
        results[i] = c.call("add", i, i)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(20)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {i: 2 * i for i in range(20)}
    c.close()


def test_push(server):
    got = []
    event = threading.Event()

    def handler(conn):
        conn.push("news", "hello")
        return True

    server.register("trigger", handler)
    c = RpcClient(server.address)
    c.on_push("news", lambda payload: (got.append(payload), event.set()))
    assert c.call("trigger")
    assert event.wait(5)
    assert got == ["hello"]
    c.close()


def test_connect_failure_fast():
    c = RpcClient("127.0.0.1:1")  # nothing listens there
    old = config.rpc_connect_timeout_s
    config.set("rpc_connect_timeout_s", 0.3)
    try:
        with pytest.raises(RpcConnectionError):
            c.call("echo", 1)
    finally:
        config.set("rpc_connect_timeout_s", old)


def test_chaos_injection(server):
    config.set("testing_rpc_failure", "echo:1.0:0.0")
    try:
        c = RpcClient(server.address)
        with pytest.raises(RpcConnectionError, match="chaos"):
            c.call("echo", 1, retryable=False)
    finally:
        config.set("testing_rpc_failure", "")
        c.close()


def test_client_pool(server):
    pool = ClientPool()
    c1 = pool.get(server.address)
    c2 = pool.get(server.address)
    assert c1 is c2
    assert c1.call("add", 1, 1) == 2
    pool.close_all()


def test_serialization_zero_copy_roundtrip():
    arr = np.arange(1 << 16, dtype=np.float32).reshape(256, 256)
    frame = serialization.pack({"x": arr, "tag": "t"})
    out = serialization.unpack(frame)
    assert out["tag"] == "t"
    np.testing.assert_array_equal(out["x"], arr)


def test_serialization_closure():
    y = 10
    frame = serialization.pack(lambda x: x + y)
    assert serialization.unpack(frame)(5) == 15
