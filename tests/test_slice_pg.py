"""SlicePlacementGroup tests (parity model: reference ray.util.tpu slice
gang scheduling, python/ray/tests on tpu pod scheduling)."""

import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster


@pytest.fixture
def tpu_cluster():
    """4 fake TPU hosts forming one v5litepod-16 slice (4 chips each;
    host 0 carries the slice-head resource, as worker 0 would)."""
    c = Cluster()
    try:
        c.add_node(num_cpus=1, num_tpus=4,
                   resources={"TPU-v5litepod-16-head": 1})
        for _ in range(3):
            c.add_node(num_cpus=1, num_tpus=4)
        ray_tpu.init(address=c.address)
        yield c
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            c.shutdown()


def test_slice_reserved_as_gang(tpu_cluster):
    from ray_tpu.accelerators import slice_placement_group

    spg = slice_placement_group("v5litepod-16", chips_per_host=4)
    assert spg.num_workers_per_slice == 4
    assert spg.wait(60), "slice not schedulable"
    locs = spg.placement_group.table()["bundle_locations"]
    # one bundle per host, all four hosts used
    assert len(set(locs.values())) == 4
    # bundle 0 (the head bundle) landed on the head-resource node
    head_node = next(
        n for n in tpu_cluster.nodes
        if n.node_id == locs[0]
    )
    assert head_node is not None

    # a worker actor pinned to each slice host via the bundle strategy
    @ray_tpu.remote(num_cpus=0, num_tpus=4)
    class SliceWorker:
        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    workers = [
        SliceWorker.options(
            scheduling_strategy=spg.worker_strategy(0, i)
        ).remote()
        for i in range(4)
    ]
    nodes = ray_tpu.get([w.node.remote() for w in workers], timeout=120)
    assert sorted(nodes) == sorted(locs[i] for i in range(4))
    env = spg.coordinator_env("10.0.0.1:8081", slice_id=0)
    assert env["MEGASCALE_NUM_SLICES"] == "1"
    spg.remove()


def test_slice_infeasible_without_head(tpu_cluster):
    from ray_tpu.accelerators import slice_placement_group

    # no node advertises a v9-head resource -> stays pending
    spg = slice_placement_group("v9pod-16", chips_per_host=4)
    assert not spg.wait(1.5)
    spg.remove()
