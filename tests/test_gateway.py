"""Remote-driver gateway tests (parity model: the reference ray://
client, python/ray/util/client/ARCHITECTURE.md — a driver that can
reach ONLY the head endpoint gets full cluster semantics)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster


@pytest.fixture()
def gw_cluster():
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    yield cluster
    try:
        ray_tpu.shutdown()
    finally:
        cluster.shutdown()


def test_remote_driver_core_semantics(gw_cluster):
    """Tasks, big objects, actors, named actors — all through the one
    gateway endpoint (every other address is never dialed directly; the
    reverse bind carries peer->driver traffic)."""
    ray_tpu.init(address=f"rt://{gw_cluster.gateway.address}")

    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get([f.remote(i) for i in range(4)]) == [0, 2, 4, 6]

    # big object: owner-side frame, chunk-pulled over the tunnel
    @ray_tpu.remote
    def big():
        return np.ones((512, 1024), np.float32)  # 2MB

    arr = ray_tpu.get(big.remote(), timeout=120)
    assert arr.shape == (512, 1024) and float(arr[5, 5]) == 1.0

    # driver put consumed by a cluster task (worker pulls FROM the
    # driver through the reverse bind)
    payload = np.full((256, 1024), 3.0, np.float32)  # 1MB
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def consume(a):
        return float(a[0, 0]) + a.shape[0]

    assert ray_tpu.get(consume.remote(ref), timeout=120) == 259.0

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def inc(self):
            self.x += 1
            return self.x

    c = Counter.options(name="gw-ctr").remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    c2 = ray_tpu.get_actor("gw-ctr")
    assert ray_tpu.get(c2.inc.remote(), timeout=60) == 2


def test_gateway_info_and_discovery(gw_cluster):
    from ray_tpu.utils import gateway as gateway_mod

    info = gateway_mod.fetch_info(gw_cluster.gateway.address)
    assert info["control_address"] == gw_cluster.address
