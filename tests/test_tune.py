"""ray_tpu.tune tests (parity model: python/ray/tune/tests/ —
test_tune_*.py, test_trial_scheduler.py subset)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_search_space_generation():
    from ray_tpu.tune.search import generate_trials

    space = {
        "lr": tune.loguniform(1e-4, 1e-1),
        "layers": tune.grid_search([1, 2]),
        "units": tune.choice([16, 32]),
        "fixed": 7,
    }
    trials = generate_trials(space, num_samples=3, seed=0)
    assert len(trials) == 6  # 2 grid points x 3 samples
    assert {t["layers"] for t in trials} == {1, 2}
    assert all(1e-4 <= t["lr"] <= 1e-1 for t in trials)
    assert all(t["fixed"] == 7 for t in trials)
    # deterministic under a seed
    again = generate_trials(space, num_samples=3, seed=0)
    assert [t["lr"] for t in again] == [t["lr"] for t in trials]


def test_asha_scheduler_unit():
    s = tune.ASHAScheduler(metric="acc", mode="max", max_t=27,
                           grace_period=1, reduction_factor=3)
    # 3 trials at rung 1: worst one stops
    assert s.on_result("a", {"training_iteration": 1, "acc": 0.9}) == "CONTINUE"
    assert s.on_result("b", {"training_iteration": 1, "acc": 0.8}) == "CONTINUE"
    assert s.on_result("c", {"training_iteration": 1, "acc": 0.1}) == "STOP"
    # horizon reached stops
    assert s.on_result("a", {"training_iteration": 27, "acc": 0.99}) == "STOP"


def test_mlp_sweep_with_asha(rt, tmp_path):
    """End-to-end sweep: tiny numpy MLP on a fixed regression problem.
    The hopeless configs run FOREVER unless ASHA stops them — so the test
    completing at all proves early stopping (timing-free: on a 1-core
    host the controller's poll latency is seconds, so any assertion that
    races natural trial completion is flaky)."""

    def trainable(config):
        import time

        rng = np.random.default_rng(0)
        X = rng.normal(size=(128, 4))
        w_true = np.asarray([1.0, -2.0, 0.5, 3.0])
        y = X @ w_true
        w = np.zeros(4)
        step = 0
        while True:
            step += 1
            if step > 50_000:
                # ASHA must have stopped this trial long ago: fail loudly
                # instead of hanging the suite forever
                raise RuntimeError("hopeless trial was never early-stopped")
            grad = -2 * X.T @ (y - X @ w) / len(y)
            w -= config["lr"] * grad
            loss = float(np.mean((y - X @ w) ** 2))
            tune.report({"loss": loss, "training_iteration": step})
            if config["lr"] > 1e-3 and step >= 30:
                return  # good configs converge and finish on their own
            time.sleep(0.05)

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.2, 0.05, 1e-5, 1e-6])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=1,
            max_concurrent_trials=2,
            scheduler=tune.ASHAScheduler(
                metric="loss", mode="min", max_t=10_000,
                grace_period=3, reduction_factor=2,
            ),
        ),
        run_dir=str(tmp_path / "sweep"),
    )
    results = tuner.fit()
    assert len(results) == 4
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.config["lr"] in (0.2, 0.05)
    assert best.metrics["loss"] < 1e-2
    stopped = [r for r in results if r.stopped_early]
    # the hopeless configs MUST be stopped (they never terminate on their
    # own); ASHA may legitimately also cut the worse of the two good lrs
    # at a rung, so assert containment, not equality
    stopped_lrs = {r.config["lr"] for r in stopped}
    assert {1e-5, 1e-6} <= stopped_lrs, (
        f"ASHA failed to stop the hopeless trials (stopped: {stopped_lrs})"
    )


def test_trial_checkpointing(rt, tmp_path):
    def trainable(config):
        for step in range(3):
            tune.report(
                {"score": step}, checkpoint={"step": step, "w": [1, 2, 3]}
            )

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.choice([1])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_dir=str(tmp_path / "ckpt"),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.checkpoint_path is not None
    state = tune.load_checkpoint(best.checkpoint_path)
    assert state["step"] == 2 and state["w"] == [1, 2, 3]


def test_trial_error_reported(rt, tmp_path):
    def trainable(config):
        if config["boom"]:
            raise ValueError("exploded")
        tune.report({"score": 1})

    tuner = tune.Tuner(
        trainable,
        param_space={"boom": tune.grid_search([False, True])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_dir=str(tmp_path / "err"),
    )
    results = tuner.fit()
    assert results.num_errors == 1
    assert results.get_best_result().metrics["score"] == 1


def test_class_trainable(rt):
    """Trainable subclass: setup/step/checkpoint loop (parity:
    reference tune/trainable/)."""
    from ray_tpu import tune

    class Quad(tune.Trainable):
        def setup(self, config):
            self.x = config["x"]
            self.total = 0.0

        def step(self):
            self.total += self.x
            return {"score": self.total,
                    "done": self.iteration >= 4}

        def save_checkpoint(self):
            return {"total": self.total}

        def load_checkpoint(self, state):
            self.total = state["total"]

    tuner = tune.Tuner(
        Quad,
        param_space={"x": tune.grid_search([1.0, 3.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.config["x"] == 3.0
    assert best.metrics["score"] == 15.0  # 5 steps x 3.0
    assert best.checkpoint_path  # auto-checkpoints landed


def test_pbt_exploit_mutates_config_mid_run(rt):
    """PBT sweep: a losing trial clones a winner's checkpoint with a
    mutated lr mid-run (VERDICT round-3 item 10)."""
    from ray_tpu import tune

    class LrTrial(tune.Trainable):
        def setup(self, config):
            self.lr = config["lr"]
            self.weight = 0.0

        def step(self):
            import time

            # good lr climbs fast; bad lr crawls — PBT should move the
            # loser onto the winner's weights + a mutated lr. The sleep
            # paces steps slower than the controller's poll period so
            # perturbation decisions happen MID-run.
            time.sleep(0.15)
            self.weight += self.lr
            return {"score": self.weight,
                    "done": self.iteration >= 11}

        def save_checkpoint(self):
            return {"weight": self.weight}

        def load_checkpoint(self, state):
            self.weight = state["weight"]

    pbt = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0, 2.0]}, quantile_fraction=0.5,
        seed=3,
    )
    tuner = tune.Tuner(
        LrTrial,
        param_space={"lr": tune.grid_search([0.01, 2.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=pbt,
            max_concurrent_trials=2,
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    assert pbt.exploit_count >= 1, "no PBT exploit happened"
    exploited = [r for r in grid if r.exploited_from]
    assert exploited, "no trial was cloned"
    # the exploited trial now carries a mutated lr from the mutation set
    assert exploited[0].config["lr"] in (0.1, 1.0, 2.0)
    # and its weight jumped to the winner's trajectory: final score far
    # above what lr=0.01 alone could reach (12 * 0.01)
    assert exploited[0].metrics["score"] > 1.0


def test_with_resources_per_trial(rt):
    """Per-trial resource requests gate trial concurrency through the
    scheduler (parity: tune.with_resources)."""
    from ray_tpu import tune

    def trainable(config):
        import time

        time.sleep(0.2)
        tune.report({"score": config["x"]})

    wrapped = tune.with_resources(trainable, {"CPU": 2.0})
    tuner = tune.Tuner(
        wrapped,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", max_concurrent_trials=3,
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    assert grid.get_best_result().metrics["score"] == 3


def test_tpe_searcher_converges_unit():
    """TPE beats random on a smooth 1-D objective: after warmup, its
    suggestions concentrate near the optimum (x*=0.3)."""
    from ray_tpu.tune.search import TPESearcher

    searcher = TPESearcher(metric="loss", mode="min", n_startup=10, seed=0)
    searcher.set_search_space({"x": tune.uniform(0.0, 1.0)})
    for i in range(60):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        loss = (cfg["x"] - 0.3) ** 2
        searcher.on_trial_complete(tid, {"loss": loss})
    late = []
    for i in range(20):
        tid = f"probe{i}"
        cfg = searcher.suggest(tid)
        late.append(cfg["x"])
        searcher.on_trial_complete(tid, {"loss": (cfg["x"] - 0.3) ** 2})
    mean_err = sum(abs(x - 0.3) for x in late) / len(late)
    assert mean_err < 0.15, f"TPE not concentrating: mean err {mean_err}"


def test_tpe_categorical_unit():
    from ray_tpu.tune.search import TPESearcher

    searcher = TPESearcher(metric="score", mode="max", n_startup=8, seed=1)
    searcher.set_search_space({"opt": tune.choice(["bad", "good", "worse"])})
    for i in range(40):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        score = {"bad": 0.2, "good": 1.0, "worse": 0.0}[cfg["opt"]]
        searcher.on_trial_complete(tid, {"score": score})
    picks = [searcher.suggest(f"p{i}")["opt"] for i in range(10)]
    assert picks.count("good") >= 7


def test_hyperband_scheduler_unit():
    """Brackets have different grace periods; early trials in the most
    aggressive bracket stop at rung 1 while the conservative bracket
    lets them run."""
    hb = tune.HyperBandScheduler(metric="acc", mode="max", max_t=9,
                                 grace_period=1, reduction_factor=3)
    assert len(hb.brackets) == 3  # grace 1, 3, 9
    hb.on_trial_add("a", {})  # bracket 0 (grace 1)
    hb.on_trial_add("b", {})  # bracket 1 (grace 3)
    # bracket 0 judges at iteration 1; a bad report can stop there
    from ray_tpu.tune import schedulers as sched_mod

    for v in (0.9, 0.8, 0.7):
        hb.brackets[0].on_result(f"seed{v}", {"acc": v,
                                              "training_iteration": 1})
    out_a = hb.on_result("a", {"acc": 0.01, "training_iteration": 1})
    assert out_a == sched_mod.STOP
    # bracket 1's first rung is 3: iteration-1 reports never stop it
    out_b = hb.on_result("b", {"acc": 0.01, "training_iteration": 1})
    assert out_b == sched_mod.CONTINUE


def test_tpe_end_to_end_with_tuner(rt, tmp_path):
    """Model-based search wired through the Tuner: configs come from
    suggest(), completions feed back, best result lands near optimum."""
    from ray_tpu.tune.search import TPESearcher

    def objective(config):
        loss = (config["x"] - 0.5) ** 2 + 0.01
        tune.report(loss=loss)

    searcher = TPESearcher(metric="loss", mode="min", n_startup=6, seed=2)
    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=18,
            max_concurrent_trials=3, search_alg=searcher,
        ),
        run_dir=str(tmp_path),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["loss"] < 0.05  # found the basin


def test_trial_restores_after_runner_death(rt, tmp_path):
    """Kill a trial's runner process mid-run: with max_failures, the
    trial restores from its latest checkpoint and completes (reference
    FailureConfig + tune controller restore)."""
    import os

    def trainable(config):
        import os as os_mod

        ckpt = tune.get_checkpoint()
        start = (tune.load_checkpoint(ckpt)["step"] + 1) if ckpt else 0
        marker = config["marker"]
        for step in range(start, 6):
            tune.report(step=step, score=float(step),
                        checkpoint={"step": step})
            if step == 2 and not os_mod.path.exists(marker):
                open(marker, "w").close()
                os_mod.kill(os_mod.getpid(), 9)  # die mid-trial, once

    marker = str(tmp_path / "died_once")
    tuner = tune.Tuner(
        trainable,
        param_space={"marker": marker},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=1, max_failures=2,
        ),
        run_dir=str(tmp_path / "run"),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.error is None, best.error
    assert best.metrics["score"] == 5.0
    assert os.path.exists(marker)  # it really did die once
    # restored from checkpoint: steps stay monotone with no restart
    # duplicates (a from-scratch restart would re-report step 0; reports
    # still buffered in the killed runner are legitimately lost)
    steps = [r["step"] for r in best.all_reports]
    assert steps[-1] == 5
    assert steps == sorted(set(steps))
