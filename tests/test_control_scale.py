"""Control-plane scale envelope (ISSUE 14): WAL group-commit crash
atomicity and determinism, batched actor lifecycle (register_actors /
kill_actors) semantics and HA-replay determinism, and a tier-1-sized
batched register + parallel kill-drain smoke.

The crash test kills a child process with SIGKILL while it is appending
inside an open group-commit window: recovery must see exactly a
contiguous prefix of the applied ops (the group is one contiguous write
of whole frames, so a torn tail is always a whole-frame prefix), and
every op the child ACKED through ``barrier()`` — the store acks RPCs
only after that barrier — must be in the prefix."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.core.control_store import ControlStore
from ray_tpu.core.ha.wal import FileBackend, HAState
from ray_tpu.utils.rpc import RpcClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _canon(o):
    """Canonical (object-identity-independent) form of the durable
    tables — same helper as test_ha_failover.py."""
    if isinstance(o, dict):
        return [[repr(k), _canon(v)] for k, v in o.items()]
    if isinstance(o, (list, tuple)):
        return [_canon(v) for v in o]
    if isinstance(o, bytes):
        return "b:" + o.hex()
    return o


def _canonical_bytes(tables) -> bytes:
    return json.dumps(_canon(tables)).encode()


# -- WAL group commit ----------------------------------------------------

_CRASH_CHILD = textwrap.dedent("""
    import sys
    sys.path.insert(0, sys.argv[1])
    from ray_tpu.core.ha.wal import FileBackend, HAState

    ha = HAState(FileBackend(sys.argv[2]), compact_entries=10**9,
                 fsync=False, group_commit_ms=25.0)
    ha.recover()
    ha.start(lambda: {"kv": {}})
    applied = {}
    state = lambda: {"kv": dict(applied)}
    i = 0
    while True:
        key = "k%06d" % i
        ha.append("kv_put", (key, "v%d" % i), state)
        applied[key] = "v%d" % i
        if i % 100 == 99:
            # the store's post-dispatch hook: ack only after the barrier
            ha.barrier()
            print("ACK", i, flush=True)
        i += 1
""")


def _replay_kv(path):
    """Recover the child's kv projection: snapshot tables + WAL tail
    replayed through the same trivial mutation."""
    ha = HAState(FileBackend(path))
    tables, records = ha.recover()
    kv = dict((tables or {}).get("kv", {}))
    for op, args in records:
        assert op == "kv_put"
        kv[args[0]] = args[1]
    ha.backend.close()
    return kv


def test_group_commit_crash_atomicity(tmp_path):
    """kill -9 while appends sit in an open group-commit window: the
    durable projection is a byte-identical CONTIGUOUS prefix of the
    applied sequence, covering at least every acked op."""
    path = str(tmp_path / "crash.db")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CRASH_CHILD, REPO, path],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        acked = -1
        for _ in range(3):
            line = proc.stdout.readline()
            assert line.startswith("ACK"), f"child failed: {line!r}"
            acked = int(line.split()[1])
        # more appends are in flight past the last barrier — kill NOW,
        # mid-window
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        proc.kill()
        proc.stdout.close()

    kv = _replay_kv(path)
    n = len(kv)
    assert n > acked  # acked implies durable, even across kill -9
    # contiguous applied prefix, values byte-identical — no holes, no
    # partial mid-group record
    assert kv == {"k%06d" % j: "v%d" % j for j in range(n)}


def test_group_commit_wal_bytes_match_per_op(tmp_path):
    """The same op sequence produces a byte-identical WAL whether frames
    land one write per op or grouped: group commit changes write-call
    granularity only, never content (close() flushes the open window)."""
    ops = [("kv_put", ("ns", "k%d" % i, b"v" * (i % 7))) for i in range(200)]
    wal_paths = {}
    for mode, ms in (("group", 50.0), ("per_op", 0.0)):
        path = str(tmp_path / f"{mode}.db")
        ha = HAState(FileBackend(path), compact_entries=10**9,
                     group_commit_ms=ms)
        ha.recover()
        ha.start(lambda: {})
        for op, args in ops:
            ha.append(op, args, lambda: {})
        ha.close()
        wal_paths[mode] = path + ".wal"
    with open(wal_paths["group"], "rb") as f:
        grouped = f.read()
    with open(wal_paths["per_op"], "rb") as f:
        per_op = f.read()
    assert grouped and grouped == per_op


# -- batched actor lifecycle against the store --------------------------


def _spec(i, job_id, name=None, ns="default"):
    spec = {
        "actor_id": "%032x" % i,
        "job_id": job_id,
        "class_name": "Bulk",
        "resources": {"CPU": 1.0},
        "max_restarts": 0,
    }
    if name:
        spec["name"] = name
        spec["namespace"] = ns
    return spec


def test_batched_lifecycle_replay_determinism(tmp_path):
    """register_actors + kill_actors land per-record WAL ops: crash
    recovery (WAL tail replay, no final snapshot) rebuilds byte-identical
    durable tables, exactly as with the singular RPCs."""
    path = str(tmp_path / "bulk.db")
    cs = ControlStore("sessK" + "0" * 26, persistence_path=path)
    cs.start()
    client = RpcClient(cs.address, name="bulk")
    job_id = client.call("register_job", driver_address="d:1", metadata={})
    specs = [_spec(i, job_id) for i in range(20)]
    res = client.call("register_actors", specs=specs)
    assert [r["ok"] for r in res] == [True] * 20
    res = client.call(
        "kill_actors", actor_ids=[s["actor_id"] for s in specs[:10]]
    )
    assert all(r["ok"] and r["changed"] for r in res)
    # idempotent: re-killing a dead actor acks without a state change
    # (a retried batch must not fail on records already landed)
    res = client.call("kill_actors", actor_ids=[specs[0]["actor_id"]])
    assert res == [
        {"actor_id": specs[0]["actor_id"], "ok": True, "changed": False}
    ]
    client.close()

    live = _canonical_bytes(cs._durable_state_snapshot())
    # simulate a crash: detach the durable log so stop() writes no final
    # snapshot — recovery then has only the WAL tail
    ha, cs._ha = cs._ha, None
    ha.backend.close()
    cs.stop()

    cs2 = ControlStore("sessL" + "0" * 26, persistence_path=path)
    cs2.start()
    try:
        assert _canonical_bytes(cs2._durable_state_snapshot()) == live
        assert cs2._ha.stats()["wal_replayed"] > 0
    finally:
        cs2.stop()


def test_bulk_register_bad_spec_does_not_poison_batch():
    """Per-record results: a name conflict (and a malformed spec) report
    their error without failing — or registering — their siblings."""
    cs = ControlStore("sessM" + "0" * 26)
    cs.start()
    try:
        client = RpcClient(cs.address, name="mix")
        job_id = client.call(
            "register_job", driver_address="d:1", metadata={}
        )
        specs = [
            _spec(100, job_id, name="dup", ns="ns1"),
            _spec(101, job_id, name="dup", ns="ns1"),  # conflict
            _spec(102, job_id),
        ]
        res = client.call("register_actors", specs=specs)
        assert [r["ok"] for r in res] == [True, False, True]
        assert "already taken" in res[1]["error"]
        ids = {a["actor_id"] for a in client.call("list_actors")}
        assert specs[0]["actor_id"] in ids
        assert specs[2]["actor_id"] in ids
        assert specs[1]["actor_id"] not in ids
        # malformed record (no actor_id): its slot reports the error
        res = client.call(
            "register_actors", specs=[{"job_id": job_id}, _spec(103, job_id)]
        )
        assert res[0]["ok"] is False and "actor_id" in res[0]["error"]
        assert res[1]["ok"] is True
        client.close()
    finally:
        cs.stop()


# -- tier-1 smoke: batched register + parallel kill-drain ---------------


def test_batched_lifecycle_smoke_200(rt_init):
    """200 actors on 4 CPUs: the client batcher coalesces the
    registrations (most stay PENDING), the alive cohort still answers,
    then a batched kill drains everything through the parallel teardown
    path — and a submit after kill fails deterministically."""
    from ray_tpu.core.worker import global_worker

    @ray_tpu.remote(num_cpus=1)
    class S:
        def ping(self):
            return 1

    # alive cohort first — it owns the capacity; which of a
    # simultaneously-registered batch wins placement is the scheduler's
    # choice, so pinging an arbitrary member of the pile would block
    alive = [S.remote() for _ in range(4)]
    assert ray_tpu.get([a.ping.remote() for a in alive], timeout=120) == [1] * 4
    actors = alive + [S.remote() for _ in range(196)]
    w = global_worker()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if len(w.control.call("list_actors")) >= 200:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("batched registrations did not land")
    # the alive cohort must still answer beneath the pending pile
    assert ray_tpu.get(alive[0].ping.remote(), timeout=120) == 1

    for a in actors:
        ray_tpu.kill(a)
    deadline = time.monotonic() + 120
    states = set()
    while time.monotonic() < deadline:
        states = {a["state"] for a in w.control.call("list_actors")}
        if states == {"DEAD"}:
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"kill drain incomplete: {states}")

    with pytest.raises(
        (ray_tpu.exceptions.ActorDiedError, ray_tpu.exceptions.TaskError)
    ):
        ray_tpu.get(alive[0].ping.remote(), timeout=30)
