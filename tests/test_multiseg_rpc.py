"""Multi-segment scatter-gather RPC framing (utils/rpc.py).

Covers the wire-format contract the zero-copy data plane rests on:
segment round-trips (ndarrays out-of-band, Frames as raw segments,
zero-length and giant segments), mixed-version compat in both directions
(legacy reader <- new writer forced in-band, new reader <- legacy
writer), torn-write / connection-drop recovery, and a chaos leg driving
``maybe_inject_response_failure`` over multi-segment replies."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from ray_tpu.utils import rpc, serialization
from ray_tpu.utils.config import config


def _pipe(msg, allow_multiseg=None):
    """Encode -> socket -> recv_message round trip."""
    a, b = socket.socketpair()
    try:
        t = threading.Thread(
            target=lambda: rpc._send_buffers(
                a, rpc.encode_message(msg, allow_multiseg=allow_multiseg),
                threading.Lock(),
            )
        )
        t.start()
        out = rpc.recv_message(b)
        t.join()
        return out
    finally:
        a.close()
        b.close()


def test_control_messages_stay_legacy_framed():
    msg = ("req", 7, "kv_put", ("ns", "k"), {"value": b"v"})
    bufs = rpc.encode_message(msg)
    # no out-of-band buffers -> single [len][pickle] frame, readable by a
    # pre-multiseg peer
    (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
    assert first != rpc._MULTISEG_MAGIC
    assert _pipe(msg) == msg


def test_ndarray_rides_out_of_band():
    arr = np.random.rand(256, 257)
    msg = ("resp", 1, True, arr)
    bufs = rpc.encode_message(msg)
    (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
    assert first == rpc._MULTISEG_MAGIC
    # the array's bytes appear as a raw trailing segment, not inside meta
    assert any(
        isinstance(b, memoryview) and b.nbytes == arr.nbytes for b in bufs
    )
    got = _pipe(msg)
    assert np.array_equal(got[3], arr)


def test_frame_rides_as_raw_segment_and_degrades_inband():
    payload = serialization.Frame(b"\xab" * 500_000)
    msg = ("resp", 2, True, ("frame", payload))
    got = _pipe(msg)  # multiseg
    assert bytes(serialization.as_view(got[3][1])) == b"\xab" * 500_000
    got = _pipe(msg, allow_multiseg=False)  # forced legacy (old reader)
    assert bytes(serialization.as_view(got[3][1])) == b"\xab" * 500_000


def test_zero_length_segments():
    # the big array lifts the frame over FRAME_OOB_MIN, so the empty
    # arrays genuinely ride as zero-length wire segments beside it
    big = np.arange(100_000, dtype=np.float64)
    msg = ("resp", 3, True, [np.zeros(0), np.zeros((0, 7)), big])
    bufs = rpc.encode_message(msg)
    (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
    assert first == rpc._MULTISEG_MAGIC
    got = _pipe(msg)
    assert got[3][0].size == 0 and got[3][1].shape == (0, 7)
    assert np.array_equal(got[3][2], big)


def test_small_buffer_messages_stay_legacy_framed():
    # a tiny ndarray must NOT quadruple the frame's syscall count: below
    # FRAME_OOB_MIN the writer re-pickles in-band
    msg = ("req", 11, "step", (np.ones(4, dtype=np.float32),), {})
    bufs = rpc.encode_message(msg)
    (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
    assert first != rpc._MULTISEG_MAGIC
    got = _pipe(msg)
    assert np.array_equal(got[3][0], np.ones(4, dtype=np.float32))


def test_many_segments_round_trip():
    arrays = [np.full((i + 1,), i, dtype=np.int64) for i in range(100)]
    got = _pipe(("resp", 4, True, arrays))
    for i, a in enumerate(got[3]):
        assert np.array_equal(a, arrays[i])


@pytest.mark.slow
def test_gigabyte_segment_round_trip():
    big = np.zeros((1 << 30) + 17, dtype=np.uint8)  # > 1 GiB, odd length
    big[[0, 1 << 20, -1]] = (1, 2, 3)
    got = _pipe(("resp", 5, True, big))
    arr = got[3]
    assert arr.nbytes == big.nbytes
    assert arr[0] == 1 and arr[1 << 20] == 2 and arr[-1] == 3


def test_new_reader_accepts_legacy_writer_frames():
    # a pre-multiseg peer frames with [u64 len][pickle] only
    msg = ("resp", 6, True, {"x": np.arange(10)})
    payload = serialization.dumps(msg)
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("<Q", len(payload)) + payload)
        got = rpc.recv_message(b)
        assert np.array_equal(got[3]["x"], np.arange(10))
    finally:
        a.close()
        b.close()


def test_config_kill_switch_forces_legacy_frames():
    config.set("rpc_multiseg", False)
    try:
        bufs = rpc.encode_message(("resp", 8, True, np.arange(1000)))
        (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
        assert first != rpc._MULTISEG_MAGIC  # old readers stay compatible
        # payload wrapping must honor the switch too: a Frame pickles as
        # a global reference a pre-multiseg peer cannot resolve, so with
        # the switch off payloads stay plain bytes end to end
        raw = b"z" * 100_000
        assert serialization.maybe_frame(raw) is raw
    finally:
        config.set("rpc_multiseg", True)
    assert isinstance(
        serialization.maybe_frame(b"z" * 100_000), serialization.Frame
    )


def test_oversegmented_messages_fall_back_inband():
    # >_MAX_SEGS tiny arrays (sum over the OOB floor): the sender must
    # not emit a frame the receiver would reject as malformed
    arrays = [np.zeros(1, dtype=np.float64) for _ in range(rpc._MAX_SEGS + 8)]
    bufs = rpc.encode_message(("resp", 12, True, arrays))
    (first,) = struct.unpack("<Q", bytes(bufs[0])[:8])
    assert first != rpc._MULTISEG_MAGIC
    got = _pipe(("resp", 12, True, arrays[:64]))  # round-trip sanity
    assert len(got[3]) == 64


def test_bad_frame_length_rejected_not_hung():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("<Q", 1 << 60))  # absurd legacy length
        with pytest.raises(ConnectionError):
            rpc.recv_message(b)
    finally:
        a.close()
        b.close()


def test_torn_multiseg_frame_surfaces_connection_error():
    arr = np.arange(100_000, dtype=np.float64)
    bufs = rpc.encode_message(("resp", 9, True, arr))
    joined = b"".join(bytes(x) for x in bufs)
    a, b = socket.socketpair()

    def tear():  # send from a thread: half a frame overflows the buffer
        a.sendall(joined[: len(joined) // 2])
        a.close()  # connection dies mid-segment

    t = threading.Thread(target=tear)
    t.start()
    try:
        with pytest.raises(ConnectionError):
            rpc.recv_message(b)
    finally:
        t.join()
        b.close()


def test_server_survives_torn_frame_and_keeps_serving():
    srv = rpc.RpcServer("torn-test")
    srv.register("echo", lambda conn, x: x)
    srv.start()
    try:
        # half a multiseg frame, then drop the connection
        bufs = rpc.encode_message(("req", 1, "echo", (np.arange(50_000),), {}))
        joined = b"".join(bytes(x) for x in bufs)
        raw = socket.create_connection(("127.0.0.1", srv.port))
        raw.sendall(joined[: len(joined) // 2])
        raw.close()
        time.sleep(0.1)
        # a fresh client still gets served, ndarrays intact
        cli = rpc.RpcClient(srv.address, name="torn-cli")
        cli.connect()
        try:
            out = cli.call("echo", np.arange(1234))
            assert np.array_equal(out, np.arange(1234))
        finally:
            cli.close()
    finally:
        srv.stop()


def test_client_retries_through_mid_reply_connection_drop():
    """A server that tears the connection halfway through a multiseg
    reply, then serves the retry completely: a retryable call must ride
    it out and return intact data."""
    arr = np.arange(200_000, dtype=np.float64)
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(4)
    port = listener.getsockname()[1]
    attempts = []

    def serve():
        for attempt in range(2):
            conn, _ = listener.accept()
            attempts.append(attempt)
            msg = rpc.recv_message(conn)
            reply = rpc.encode_message(("resp", msg[1], True, arr))
            joined = b"".join(bytes(x) for x in reply)
            if attempt == 0:
                conn.sendall(joined[: len(joined) // 3])  # torn mid-segment
                conn.close()
            else:
                conn.sendall(joined)
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    cli = rpc.RpcClient(f"127.0.0.1:{port}", name="retry-cli")
    try:
        out = cli.call("echo", timeout_s=10.0, retryable=True)
        assert np.array_equal(out, arr)
        assert len(attempts) == 2
    finally:
        cli.close()
        listener.close()
    t.join(timeout=5)


def test_chaos_injection_over_multiseg_replies():
    """maybe_inject_response_failure fires on calls whose replies are
    multi-segment frames; retryable calls must absorb both request- and
    response-side injections and still return correct ndarrays."""
    srv = rpc.RpcServer("chaos-test")
    arr = np.random.rand(64, 64)
    srv.register("get_arr", lambda conn, i: arr * i)
    srv.start()
    cli = rpc.RpcClient(srv.address, name="chaos-cli")
    cli.connect()
    config.set("testing_rpc_failure", "get_arr:0.2:0.2")
    try:
        for i in range(40):
            # outer retry absorbs the (possible) exhaustion of the
            # client's own budget — the assertion under test is payload
            # INTEGRITY across injected request/response failures
            for attempt in range(5):
                try:
                    out = cli.call("get_arr", i, retryable=True, timeout_s=10.0)
                    break
                except rpc.RpcConnectionError:
                    if attempt == 4:
                        raise
            assert np.array_equal(out, arr * i)
    finally:
        config.set("testing_rpc_failure", "")
        cli.close()
        srv.stop()
