"""Compiled graphs (ray_tpu/dag.py).

Parity model: reference python/ray/dag tests — bind/compile/execute over
static actor DAGs, channel reuse, error propagation, teardown, and the
headline property: the compiled path beats the RPC path per call.
"""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
class Adder:
    def __init__(self, delta):
        self.delta = delta

    def add(self, x):
        return x + self.delta

    def slow_add(self, x):
        time.sleep(6.0)
        return x + self.delta

    def boom(self, x):
        raise ValueError("boom")


def test_single_actor_chain(rt):
    a = Adder.remote(10)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(1).get() == 11
        assert cdag.execute(2).get() == 12
        for i in range(50):  # channel reuse across many rounds
            assert cdag.execute(i).get() == i + 10
    finally:
        cdag.teardown()


def test_two_actor_chain(rt):
    a = Adder.remote(1)
    b = Adder.remote(100)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(5).get() == 106
        assert cdag.execute(6).get() == 107
    finally:
        cdag.teardown()


def test_multi_output(rt):
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(10).get() == [11, 12]
    finally:
        cdag.teardown()


def test_error_propagates_and_dag_survives(rt):
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    cdag = dag.experimental_compile()
    try:
        with pytest.raises(ValueError, match="boom"):
            cdag.execute(1).get()
        # the loop keeps serving after an application error
        with pytest.raises(ValueError, match="boom"):
            cdag.execute(2).get()
    finally:
        cdag.teardown()


def test_actor_usable_after_teardown(rt):
    a = Adder.remote(5)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile()
    assert cdag.execute(1).get() == 6
    cdag.teardown()
    # the exec loop released the actor's executor slot
    assert rt.get(a.add.remote(10), timeout=30) == 15


def test_constant_args(rt):
    @ray_tpu.remote
    class Mixer:
        def mix(self, x, y, z):
            return (x, y, z)

    m = Mixer.remote()
    with InputNode() as inp:
        dag = m.mix.bind(inp, "const", 3)
    cdag = dag.experimental_compile()
    try:
        assert cdag.execute(1).get() == (1, "const", 3)
    finally:
        cdag.teardown()


def test_teardown_with_unconsumed_results(rt):
    """teardown() must not wedge the actor when execute() rounds were
    never consumed (the exec loop is blocked writing the unread output:
    teardown drains it). The actor must serve normal calls afterwards."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile()
    cdag.execute(1)
    cdag.execute(2)  # two unconsumed rounds: exec loop blocked on write
    t0 = time.monotonic()
    cdag.teardown()
    assert time.monotonic() - t0 < 30.0, "teardown stalled"
    # the exec-loop slot was released: plain actor calls work again
    assert rt.get(a.add.remote(10), timeout=60) == 11


def test_execute_inflight_bound(rt):
    """Unconsumed rounds beyond the channel backpressure bound raise a
    clear error instead of blocking inside execute() (reference raises
    RayCgraphCapacityExceeded)."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile()
    try:
        refs = [cdag.execute(1), cdag.execute(2)]
        with pytest.raises(RuntimeError, match="unconsumed"):
            cdag.execute(3)
        assert [r.get() for r in refs] == [2, 3]
        assert cdag.execute(4).get() == 5  # drained: capacity back
    finally:
        cdag.teardown()


def test_execute_inflight_bound_is_configurable(rt):
    """experimental_compile(max_inflight=N) streams N unconsumed rounds
    through the slot rings before raising (satellite: the bound is a
    compile knob now, not a hardcoded 2)."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile(max_inflight=4)
    try:
        refs = [cdag.execute(i) for i in range(4)]  # would raise at 2 before
        with pytest.raises(RuntimeError, match="unconsumed"):
            cdag.execute(99)
        assert [r.get() for r in refs] == [1, 2, 3, 4]
        assert cdag.execute(10).get() == 11  # drained: capacity back
    finally:
        cdag.teardown()


def test_compile_rejects_bad_bounds(rt):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    with pytest.raises(ValueError, match="max_inflight"):
        dag.experimental_compile(max_inflight=0)
    with pytest.raises(ValueError, match="channel_slots"):
        dag.experimental_compile(channel_slots=0)


def test_teardown_warns_and_unlinks_on_wedged_loop(rt, caplog):
    """A loop stuck in user code past the drain deadline: teardown must
    SAY so (not silently fall through) and still unlink every channel —
    no /dev/shm/rtchan_* debris for sweep_stale_runtime."""
    import logging
    import os

    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.slow_add.bind(inp)
    cdag = dag.experimental_compile()
    paths = [
        ch.path
        for ch in (cdag._input_channels + cdag._output_channels
                   + cdag._edge_channels)
    ]
    cdag.execute(1)
    time.sleep(0.3)  # the loop is now inside slow_add's sleep
    with caplog.at_level(logging.WARNING, logger="ray_tpu.dag"):
        t0 = time.monotonic()
        cdag.teardown(timeout_s=1.5)
        assert time.monotonic() - t0 < 6.0
    assert "still running" in caplog.text
    for p in paths:
        assert not os.path.exists(p), f"teardown leaked {p}"
        assert not os.path.exists(p + ".d"), f"teardown leaked {p}.d"


def test_multi_actor_edge_channels_unlinked(rt):
    """Actor→actor edge channels (not just driver-facing ones) are
    reclaimed at teardown."""
    import os

    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    cdag = dag.experimental_compile()
    assert len(cdag._edge_channels) == 1  # the a→b hop
    paths = [ch.path for ch in cdag._edge_channels]
    assert cdag.execute(1).get() == 4
    cdag.teardown()
    for p in paths:
        assert not os.path.exists(p), f"edge channel leaked {p}"


def test_compiled_path_beats_rpc_path(rt):
    """The headline claim (VERDICT item 2): per-call latency on the
    compiled path must be well under the remote()+get round trip."""
    a = Adder.remote(1)
    # IMPORTANT: measure the RPC path BEFORE compiling — the parked exec
    # loop occupies the actor's executor slot (dedicated actor, like the
    # reference), so remote() calls queue until teardown.
    rt.get(a.add.remote(0))
    n = 200
    t0 = time.perf_counter()
    for i in range(n):
        rt.get(a.add.remote(i))
    rpc_s = (time.perf_counter() - t0) / n

    with InputNode() as inp:
        dag = a.add.bind(inp)
    cdag = dag.experimental_compile()
    try:
        cdag.execute(0).get()
        t0 = time.perf_counter()
        for i in range(n):
            cdag.execute(i).get()
        compiled_s = (time.perf_counter() - t0) / n
    finally:
        cdag.teardown()
    # The compiled path must clearly beat RPC per call. The measured gap
    # on this 1-core CI box is ~4.5-6x (handoffs are scheduler-bound and
    # the round-4 id-hash cache sped the RPC path up too); assert a
    # conservative 3.5x so CI noise can't flake the suite, and print the
    # measured ratio (BENCH_CORE.json records it per round).
    ratio = rpc_s / compiled_s
    print(f"compiled={compiled_s*1e6:.0f}us rpc={rpc_s*1e6:.0f}us ratio={ratio:.1f}x")
    assert ratio > 3.5, f"compiled path only {ratio:.1f}x faster"
