"""Prefix KV cache (serve/prefix_cache.py + the engine's prefix-aware
admission): chain-hash determinism (including across processes — the
router's affinity hint and multi-replica pools depend on it), the
refcount/LRU pool contract, and the serving guarantee: admitting a
request from cached blocks produces bitwise-identical generations at
temperature=0, under slot churn, and with the kill switch flipped."""

import json
import subprocess
import sys
import threading

import numpy as np
import pytest

from ray_tpu.serve.prefix_cache import BlockPool, hash_blocks


# ---------------------------------------------------------------------------
# chain hashing
# ---------------------------------------------------------------------------


def test_hash_blocks_only_full_blocks():
    assert hash_blocks([], 4) == []
    assert hash_blocks([1, 2, 3], 4) == []
    assert len(hash_blocks(list(range(10)), 4)) == 2
    assert len(hash_blocks(list(range(8)), 4)) == 2


def test_hash_blocks_chain_prefix_property():
    a = hash_blocks([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12], 4)
    b = hash_blocks([1, 2, 3, 4, 5, 6, 7, 8, 99, 99, 99, 99], 4)
    assert a[:2] == b[:2] and a[2] != b[2]
    # the chain: a different FIRST block changes every downstream digest
    c = hash_blocks([9, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12], 4)
    assert all(x != y for x, y in zip(a, c))


def test_hash_blocks_deterministic_across_processes():
    """Digests are pure content hashes — another interpreter produces
    exactly the same chain (no pid/seed/hash-randomization leakage), so
    pools on different replicas agree on block identity."""
    tokens = [int(t) for t in np.random.RandomState(3).randint(0, 256, 200)]
    prog = (
        "import json, sys; from ray_tpu.serve.prefix_cache import "
        "hash_blocks; print(json.dumps(hash_blocks(json.loads("
        "sys.argv[1]), 64)))"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog, json.dumps(tokens)],
        capture_output=True, text=True, check=True,
    )
    assert json.loads(out.stdout) == hash_blocks(tokens, 64)


# ---------------------------------------------------------------------------
# block pool: refcounts + LRU
# ---------------------------------------------------------------------------


def _blk(i):
    k = np.full((2, 4, 2, 2), i, np.float32)
    return k, -k


def test_pool_match_increfs_and_caps():
    pool = BlockPool("m", block_tokens=4, max_blocks=8)
    for d in ("a", "b"):
        pool.insert(d, *_blk(1))
    pool.release(["a", "b"])
    held, ks, vs = pool.match(["a", "b", "x"], max_tokens=100)
    assert held == ["a", "b"] and len(ks) == 2
    assert pool.ref_count("a") == pool.ref_count("b") == 1
    # chain walk stops at the first absent digest
    held2, _, _ = pool.match(["a", "x", "b"], max_tokens=100)
    assert held2 == ["a"] and pool.ref_count("a") == 2
    # the cap: fewer than block_tokens usable tokens -> nothing matched
    assert pool.match(["a"], max_tokens=3)[0] == []
    pool.release(["a", "b"])
    pool.release(["a"])
    assert pool.ref_count("a") == 0
    st = pool.stats()
    assert st["hits"] == 3 and st["misses"] == 4
    pool.close()


def test_pool_lru_eviction_prefers_oldest_unreferenced():
    pool = BlockPool("m", block_tokens=4, max_blocks=2)
    for d in ("a", "b"):
        pool.insert(d, *_blk(1))
    pool.release(["a", "b"])
    pool.match(["b"], max_tokens=100)  # touch b: a is now LRU
    pool.release(["b"])
    pool.insert("c", *_blk(2))
    assert pool.resident() == 2
    assert pool.ref_count("a") == 0 and pool.match(["a"], 100)[0] == []
    assert pool.match(["b"], 100)[0] == ["b"]  # survived: recently used
    assert pool.stats()["evictions"] == 1
    pool.close()


def test_pool_pinned_blocks_survive_overflow():
    """Refs pin blocks: a pool over capacity with every block in use by
    in-flight slots evicts nothing (and recovers once refs drop)."""
    pool = BlockPool("m", block_tokens=4, max_blocks=2)
    for d in ("a", "b", "c", "d"):
        pool.insert(d, *_blk(1))  # all held: caller keeps one ref each
    assert pool.resident() == 4 and pool.stats()["evictions"] == 0
    pool.release(["a", "b", "c", "d"])
    assert pool.resident() == 2  # drained back to capacity, LRU-first
    assert pool.match(["d"], 100)[0] == ["d"]
    pool.close()


def test_pool_close_drops_everything_despite_refs():
    pool = BlockPool("m", block_tokens=4, max_blocks=8)
    pool.insert("a", *_blk(1))  # ref held
    pool.close()
    assert pool.resident() == 0
    # closed pools neither match nor re-admit
    pool.insert("b", *_blk(2))
    assert pool.resident() == 0 and pool.match(["a"], 100)[0] == []


# ---------------------------------------------------------------------------
# engine-level: cached admission == cold prefill, bit for bit
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def engine():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    srv = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=4))
    yield srv
    srv._stop.set()


def test_cached_vs_cold_generations_bitwise_identical(engine):
    """The acceptance property: a prompt admitted from pooled blocks +
    tail prefill generates EXACTLY the tokens full prefill generates at
    temperature=0 — including a block-aligned prompt (capped match) and
    with the kill switch off."""
    from ray_tpu.utils.config import config

    rng = np.random.RandomState(11)
    for n in (100, 128, 65):
        prompt = [int(t) for t in rng.randint(0, 256, n)]
        req = {"prompt_tokens": prompt, "max_new_tokens": 8,
               "temperature": 0.0}
        pool = engine._prefix_pool
        h0 = pool.stats()["hits"]
        cold = engine(req)["tokens"]
        hot = engine(req)["tokens"]
        assert hot == cold
        assert pool.stats()["hits"] > h0  # second pass came from cache
        config.set("serve_prefix_cache", False)
        try:
            off = engine(req)["tokens"]
        finally:
            config.set("serve_prefix_cache", True)
        assert off == cold


def test_refcounts_drain_under_slot_churn(engine):
    """Concurrent requests sharing a prefix churn through the KV slots;
    when they all finish, every pooled block's refcount is back to 0
    (nothing leaks pins) and the shared blocks are still resident."""
    rng = np.random.RandomState(12)
    shared = [int(t) for t in rng.randint(0, 256, 64)]
    solo = {}
    for i in range(4):
        solo[i] = engine({"prompt_tokens": shared + [i, i + 1],
                          "max_new_tokens": 6, "temperature": 0.0})["tokens"]

    results = [None] * 4

    def call(i):
        results[i] = engine({"prompt_tokens": shared + [i, i + 1],
                             "max_new_tokens": 6, "temperature": 0.0})

    threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i in range(4):
        assert results[i] is not None and results[i]["tokens"] == solo[i]
    pool = engine._prefix_pool
    assert pool.resident() > 0
    with pool._lock:
        if hasattr(pool, "_pages"):  # PagedKVPool (paged engine default)
            assert all(p.refs == 0 for p in pool._pages), {
                p.idx: p.refs for p in pool._pages if p.refs
            }
        else:  # BlockPool (RT_SERVE_PAGED_KV=0 slot engine)
            assert all(b.refs == 0 for b in pool._blocks.values()), {
                b.digest: b.refs for b in pool._blocks.values() if b.refs
            }
