"""Collective library tests: actor groups over the cpu (KV) backend —
parity model: python/ray/util/collective/tests/single_node_cpu_tests."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


@ray_tpu.remote
class Member:
    def __init__(self, rank, world):
        self.rank, self.world = rank, world

    def setup(self, group):
        from ray_tpu import collective

        collective.init_collective_group(self.world, self.rank, "cpu", group)
        return True

    def do_allreduce(self, group):
        from ray_tpu import collective

        return collective.allreduce(np.full((4,), self.rank + 1.0), group_name=group)

    def do_allgather(self, group):
        from ray_tpu import collective

        return collective.allgather(np.array([self.rank]), group_name=group)

    def do_broadcast(self, group):
        from ray_tpu import collective

        return collective.broadcast(
            np.arange(3) if self.rank == 0 else np.zeros(3), 0, group
        )

    def do_reducescatter(self, group):
        from ray_tpu import collective

        return collective.reducescatter(np.ones((4, 2)), group_name=group)

    def do_sendrecv(self, group):
        from ray_tpu import collective

        if self.rank == 0:
            collective.send(np.array([42.0]), 1, group)
            return None
        return collective.recv(0, group)


def _make_group(rt, n, group):
    members = [Member.remote(i, n) for i in range(n)]
    rt.get([m.setup.remote(group) for m in members], timeout=60)
    return members


def test_allreduce_and_allgather(rt):
    members = _make_group(rt, 2, "g1")
    out = rt.get([m.do_allreduce.remote("g1") for m in members], timeout=60)
    np.testing.assert_array_equal(out[0], np.full((4,), 3.0))
    np.testing.assert_array_equal(out[0], out[1])

    gathered = rt.get([m.do_allgather.remote("g1") for m in members], timeout=60)
    assert [int(g[0]) for g in gathered[0]] == [0, 1]


def test_broadcast_reducescatter_sendrecv(rt):
    members = _make_group(rt, 2, "g2")
    out = rt.get([m.do_broadcast.remote("g2") for m in members], timeout=60)
    np.testing.assert_array_equal(out[1], np.arange(3))

    rs = rt.get([m.do_reducescatter.remote("g2") for m in members], timeout=60)
    assert rs[0].shape == (2, 2)
    np.testing.assert_array_equal(rs[0], np.full((2, 2), 2.0))

    sr = rt.get([m.do_sendrecv.remote("g2") for m in members], timeout=60)
    np.testing.assert_array_equal(sr[1], np.array([42.0]))
