"""Disaggregated prefill/decode serving (serve/kv_transfer.py): the
prefill tier runs as its own deployment and ships KV rows to the decode
ingress over an RpcChannel. End-to-end: a disaggregated deployment must
serve /v1/chat/completions (unary + SSE, byte-identical to each other
and to a monolithic engine at temperature=0), join prefill → transfer →
engine into one trace, and FAIL requests within the disagg deadline when
the prefill replica is SIGKILLed — never hang decode on a half-open
channel."""

import http.client
import json
import os
import signal
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve, state
from ray_tpu.observability import tracing

MODEL = "tiny"
DEPLOYMENT = "disagg-llm"
PREFILL = f"{DEPLOYMENT}-prefill"


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=8)
    serve.start(http_port=0)
    yield ray_tpu
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def front(rt):
    """Disaggregated deployment: 2 decode/ingress replicas + 1 prefill
    replica, plus the proxy address serving it."""
    from ray_tpu.serve import llm as serve_llm

    serve_llm.deploy(
        {MODEL: serve_llm.LLMConfig(model_id="gpt2-tiny", max_batch_size=4)},
        name=DEPLOYMENT, num_replicas=2, route_prefix="/v1",
        disaggregated=True, prefill_replicas=1,
    )
    deadline = time.monotonic() + 60
    addrs = []
    while time.monotonic() < deadline and not addrs:
        addrs = serve.proxy_addresses()
        time.sleep(0.2)
    assert addrs, "no HTTP proxy came up"
    yield addrs[0]
    serve.delete(DEPLOYMENT)
    serve.delete(PREFILL)


def _post(addr, path, body, timeout=180):
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _stream_chat(addr, body, headers=None, timeout=180):
    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/chat/completions", body=json.dumps(body),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        raw = resp.read().decode()
        events = [b[len("data: "):] for b in raw.split("\n\n") if b.strip()]
        return resp.status, events
    finally:
        conn.close()


def _chat_body(content, **extra):
    return {"model": MODEL, "max_tokens": 8, "temperature": 0,
            "messages": [{"role": "user", "content": content}], **extra}


def test_disagg_unary_stream_and_monolithic_parity(front):
    """The acceptance request: the same chat completion through the
    disaggregated stack — unary and SSE — produces identical text, and
    that text matches a monolithic (local-prefill) engine bit for bit at
    temperature=0: remote prefill + KV import changed WHERE prefill ran,
    not what got generated."""
    addr = front
    # rendered chat prompt must leave decode room inside n_positions
    # (128 for gpt2-tiny) while still spanning a full 64-token block
    content = (
        "shared system preamble long enough to span a prefix block: "
        + "x" * 30
    )
    st, out = _post(addr, "/v1/chat/completions", _chat_body(content))
    assert st == 200, out
    text = out["choices"][0]["message"]["content"]
    assert out["usage"]["completion_tokens"] == 8

    st2, events = _stream_chat(addr, _chat_body(content, stream=True))
    assert st2 == 200 and events[-1] == "[DONE]"
    streamed = "".join(
        json.loads(e)["choices"][0]["delta"].get("content", "")
        for e in events[:-1]
    )
    assert streamed == text

    # monolithic reference: same weights recipe, local prefill
    from ray_tpu.serve.llm import LLMConfig, LLMServer
    from ray_tpu.serve.openai import tokenizer as tokenizer_mod

    tok = tokenizer_mod.ByteTokenizer()
    prompt = tok.encode(
        tokenizer_mod.render_chat(_chat_body(content)["messages"])
    )
    mono = LLMServer(LLMConfig(model_id="gpt2-tiny", max_batch_size=4))
    try:
        ref = mono({"prompt_tokens": prompt, "max_new_tokens": 8,
                    "temperature": 0.0})["tokens"]
    finally:
        mono._stop.set()
    assert text == tok.decode(ref)


def test_disagg_request_joins_one_trace(front):
    """One traced SSE request shows the full disaggregated flow: proxy,
    router, replica, engine AND the prefill + transfer legs all stamped
    with the client's trace id."""
    addr = front
    tid = "feedfacecafe0d15"
    st, events = _stream_chat(
        addr, _chat_body("trace the disaggregated path", stream=True),
        headers={tracing.TRACE_HEADER: tid},
    )
    assert st == 200 and events[-1] == "[DONE]"
    deadline = time.monotonic() + 30
    comps = set()
    spans = []
    want = {"proxy", "router", "replica", "engine", "prefill", "transfer"}
    while time.monotonic() < deadline:
        spans = [
            ev for ev in state.timeline()
            if ev.get("cat") == "request" and ev.get("ph") == "X"
            and ev["args"].get("trace_id") == tid
        ]
        comps = {ev["name"].split(":")[0] for ev in spans}
        if want <= comps:
            break
        time.sleep(0.3)
    assert want <= comps, spans
    # the prefill leg names the OpenAI model it prefilled for and ships
    # a non-trivial KV payload
    pre = next(ev for ev in spans if ev["name"] == f"prefill:{MODEL}")
    assert pre["args"].get("kv_bytes", 0) > 0
    # the legs roll up into request_summary: prefill/transfer under the
    # OpenAI model's row, TTFT (imported KV counts as cached) under the
    # engine's model-id row
    summary = state.request_summary()["deployments"]
    assert "prefill_s" in summary[MODEL]
    assert "transfer_s" in summary[MODEL]
    assert "ttft_cached_s" in summary["gpt2-tiny"]


def test_sigkilled_prefill_replica_fails_within_deadline(front, rt):
    """Kill -9 the prefill replica: an in-flight/next request must fail
    within the RT_SERVE_DISAGG_TIMEOUT_S budget (ActorDied/Timeout on
    the ack or channel read), not strand the decode side. Runs last in
    the module — the controller respawns the replica afterwards."""
    from ray_tpu.models import gpt2
    from ray_tpu.serve import kv_transfer
    from ray_tpu.utils.config import config

    h = serve.get_deployment_handle(PREFILL)
    info = h.remote({"op": "info"}).result(timeout_s=60)
    assert info["models"] == [MODEL]

    # warm-up doubles as a unit test of the driver-side orchestration:
    # the shipment has full-shape KV rows and the monolithic first token
    mcfg = gpt2.CONFIGS["gpt2-tiny"]
    prompt = [int(t) for t in np.random.RandomState(5).randint(0, 256, 70)]
    imp = kv_transfer.prefill_remote(
        PREFILL, MODEL, {"prompt_tokens": prompt, "temperature": 0.0}, mcfg
    )
    assert imp["prompt_len"] == 70
    assert imp["k"].shape == (mcfg.n_layer, 70, mcfg.n_head, mcfg.head_dim)

    os.kill(info["pid"], signal.SIGKILL)
    config.set("serve_disagg_timeout_s", 4.0)
    t0 = time.monotonic()
    try:
        with pytest.raises(Exception):
            kv_transfer.prefill_remote(
                PREFILL, MODEL,
                {"prompt_tokens": prompt, "temperature": 0.0}, mcfg,
            )
    finally:
        config.set("serve_disagg_timeout_s", 60.0)
    assert time.monotonic() - t0 < 20
