"""ray_tpu.data tests (parity model: python/ray/data/tests/ —
test_map.py, test_consumption.py, test_split.py subset)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rtd


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_range_count_take(rt):
    ds = rtd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches_streaming(rt):
    ds = rtd.range(1000, parallelism=8).map_batches(
        lambda b: {"x": b["id"] * 2}
    )
    total = 0
    seen = []
    for batch in ds.iter_batches(batch_size=128):
        assert set(batch.keys()) == {"x"}
        total += len(batch["x"])
        seen.append(batch["x"])
    assert total == 1000
    all_x = np.concatenate(seen)
    assert sorted(all_x.tolist()) == [2 * i for i in range(1000)]


def test_exact_batch_sizes(rt):
    ds = rtd.range(1000, parallelism=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=128)]
    assert all(s == 128 for s in sizes[:-1])
    assert sum(sizes) == 1000
    # drop_last drops the remainder
    sizes = [
        len(b["id"])
        for b in ds.iter_batches(batch_size=128, drop_last=True)
    ]
    assert all(s == 128 for s in sizes)
    assert sum(sizes) == 1000 - (1000 % 128)


def test_fused_map_filter_chain(rt):
    ds = (
        rtd.range(100, parallelism=4)
        .map_batches(lambda b: {"id": b["id"] + 1})
        .filter(lambda r: r["id"] % 2 == 0)
        .map_batches(lambda b: {"id": b["id"] // 2})
    )
    got = sorted(r["id"] for r in ds.take_all())
    assert got == sorted((i + 1) // 2 for i in range(100) if (i + 1) % 2 == 0)


def test_from_items_map_rows(rt):
    ds = rtd.from_items([{"v": i} for i in range(20)], parallelism=3)
    out = ds.map(lambda r: {"v": r["v"] ** 2}).take_all()
    assert sorted(r["v"] for r in out) == [i * i for i in range(20)]


def test_flat_map_and_limit(rt):
    ds = rtd.from_items(list(range(10)), parallelism=2).flat_map(
        lambda x: [x, x]
    )
    assert ds.count() == 20
    assert len(ds.limit(7).take_all()) == 7


def test_limit_stops_pipeline_early(rt):
    # limit over a large range must not require materializing everything:
    # streaming executor stops submitting upstream once satisfied
    ds = rtd.range(1_000_000, parallelism=100).limit(10)
    rows = ds.take_all()
    assert [r["id"] for r in rows] == list(range(10))


def test_repartition(rt):
    ds = rtd.range(100, parallelism=7).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 100
    got = sorted(r["id"] for r in ds.take_all())
    assert got == list(range(100))


def test_random_shuffle(rt):
    ds = rtd.range(200, parallelism=4).random_shuffle(seed=7)
    got = [r["id"] for r in ds.take_all()]
    assert sorted(got) == list(range(200))
    assert got != list(range(200))  # astronomically unlikely to be sorted


def test_union(rt):
    a = rtd.range(10, parallelism=2)
    b = rtd.range(5, parallelism=1).map_batches(lambda x: {"id": x["id"] + 100})
    assert a.union(b).count() == 15


def test_materialize_and_reuse(rt):
    ds = rtd.range(50, parallelism=4).map_batches(
        lambda b: {"id": b["id"] * 3}
    )
    mat = ds.materialize()
    assert mat.count() == 50
    assert mat.count() == 50  # second pass over cached blocks
    assert sorted(r["id"] for r in mat.take_all()) == [3 * i for i in range(50)]


def test_split_and_shard(rt):
    ds = rtd.range(100, parallelism=10)
    shards = ds.split(3)
    counts = [s.count() for s in shards]
    assert sum(counts) == 100
    ids = sorted(
        r["id"] for s in shards for r in s.take_all()
    )
    assert ids == list(range(100))
    # lazy shard() partitions the block stream the same way
    lazy = [ds.shard(3, i) for i in range(3)]
    lazy_ids = sorted(r["id"] for s in lazy for r in s.take_all())
    assert lazy_ids == list(range(100))


def test_actor_pool_map_batches(rt):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rtd.range(40, parallelism=4).map_batches(
        AddConst, fn_constructor_args=(1000,), concurrency=2
    )
    got = sorted(r["id"] for r in ds.take_all())
    assert got == [1000 + i for i in range(40)]


def test_udf_error_propagates(rt):
    def boom(batch):
        raise ValueError("bad udf")

    ds = rtd.range(10, parallelism=2).map_batches(boom)
    with pytest.raises(Exception, match="bad udf"):
        ds.take_all()


def test_read_text_json_csv(rt, tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("hello\nworld\n")
    ds = rtd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]

    j = tmp_path / "b.jsonl"
    j.write_text('{"x": 1}\n{"x": 2}\n')
    assert [r["x"] for r in rtd.read_json(str(j)).take_all()] == [1, 2]

    c = tmp_path / "c.csv"
    c.write_text("a,b\n1,2\n3,4\n")
    rows = rtd.read_csv(str(c)).take_all()
    assert [r["a"] for r in rows] == [1.0, 3.0]


def test_zero_copy_numpy_block(rt):
    arr = np.arange(300_000, dtype=np.float32)  # >100KB -> plasma path
    ds = rtd.from_numpy(arr).map_batches(lambda b: {"data": b["data"] + 1})
    out = ds.take_all()
    assert len(out) == 300_000


def test_iter_epochs(rt):
    ds = rtd.range(64, parallelism=2)
    it = ds.iterator()
    epochs = list(it.iter_epochs(2, batch_size=32))
    assert len(epochs) == 2
    for ep in epochs:
        assert sum(len(b["id"]) for b in ep) == 64


def test_train_dataset_shards(rt, tmp_path):
    """datasets= flows to workers; each rank consumes a disjoint shard and
    together the shards cover the whole dataset exactly once (parity:
    ray.train.get_dataset_shard). Requires a deterministic block-stream
    order: each worker executes the pipeline independently, so shard()
    would overlap/drop blocks if completion order leaked through."""
    import json

    from ray_tpu import train as rt_train
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    ds = rtd.range(64, parallelism=8).map_batches(lambda b: {"id": b["id"]})
    out_dir = str(tmp_path)

    def loop():
        ctx = rt_train.get_context()
        it = rt_train.get_dataset_shard("train")
        got = []
        for batch in it.iter_batches(batch_size=8):
            got.extend(int(x) for x in batch["id"])
        with open(f"{out_dir}/ids_{ctx.get_world_rank()}.json", "w") as f:
            json.dump(got, f)
        rt_train.report({"n": len(got)})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.error is None
    ids0 = json.load(open(f"{out_dir}/ids_0.json"))
    ids1 = json.load(open(f"{out_dir}/ids_1.json"))
    assert ids0 and ids1
    assert not (set(ids0) & set(ids1)), "shards overlap"
    assert sorted(ids0 + ids1) == list(range(64)), "shards don't cover dataset"


def test_train_dataset_shards_reexecute(rt, tmp_path):
    """reexecute split mode: per-rank streaming re-execution with the
    FIFO-deterministic block order still yields disjoint full coverage."""
    import json

    from ray_tpu import train as rt_train
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    ds = rtd.range(64, parallelism=8).random_shuffle()
    out_dir = str(tmp_path)

    def loop():
        ctx = rt_train.get_context()
        it = rt_train.get_dataset_shard("train")
        got = []
        for batch in it.iter_batches(batch_size=8):
            got.extend(int(x) for x in batch["id"])
        with open(f"{out_dir}/ids_{ctx.get_world_rank()}.json", "w") as f:
            json.dump(got, f)
        rt_train.report({"n": len(got)})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
        datasets={"train": ds},
        dataset_split_mode="reexecute",
    )
    result = trainer.fit()
    assert result.error is None
    ids0 = json.load(open(f"{out_dir}/ids_0.json"))
    ids1 = json.load(open(f"{out_dir}/ids_1.json"))
    assert not (set(ids0) & set(ids1)), "shards overlap"
    assert sorted(ids0 + ids1) == list(range(64))


# ---------------------------------------------------------------------------
# all-to-all tier: sort / groupby / join / exact shuffle
# (parity model: python/ray/data/tests/test_sort.py, test_groupby.py)
# ---------------------------------------------------------------------------


def test_sort_global_order(rt):
    rng = np.random.RandomState(3)
    vals = rng.randint(0, 10_000, size=500).tolist()
    ds = rtd.from_items(
        [{"v": v} for v in vals], parallelism=8
    ).sort(key="v")
    out = [r["v"] for r in ds.take_all()]
    assert out == sorted(vals)


def test_sort_descending_callable_key(rt):
    vals = [5, 3, 9, 1, 7, 2, 8]
    ds = rtd.from_items(vals, parallelism=3).sort(
        key=lambda x: x, descending=True
    )
    assert ds.take_all() == sorted(vals, reverse=True)


def test_groupby_aggregate_matches_pandas(rt):
    """>16 blocks; compare against a pandas groupby oracle (VERDICT
    round-3 item 6)."""
    import pandas as pd

    rng = np.random.RandomState(7)
    rows = [
        {"k": int(k), "v": float(v)}
        for k, v in zip(
            rng.randint(0, 23, size=800), rng.randn(800) * 10
        )
    ]
    ds = rtd.from_items(rows, parallelism=20)
    out = (
        ds.groupby("k")
        .aggregate(
            rtd.AggregateFn.count("n"),
            rtd.AggregateFn.of_column("sum", "v", "v_sum"),
            rtd.AggregateFn.of_column("mean", "v", "v_mean"),
            rtd.AggregateFn.of_column("max", "v", "v_max"),
        )
        .take_all()
    )
    got = {r["k"]: r for r in out}
    pdf = pd.DataFrame(rows).groupby("k")["v"].agg(["count", "sum", "mean", "max"])
    assert set(got) == set(pdf.index)
    for k, row in pdf.iterrows():
        assert got[k]["n"] == row["count"]
        np.testing.assert_allclose(got[k]["v_sum"], row["sum"], rtol=1e-9)
        np.testing.assert_allclose(got[k]["v_mean"], row["mean"], rtol=1e-9)
        np.testing.assert_allclose(got[k]["v_max"], row["max"], rtol=1e-9)


def test_groupby_map_groups(rt):
    rows = [{"k": i % 3, "v": i} for i in range(30)]
    out = (
        rtd.from_items(rows, parallelism=5)
        .groupby("k")
        .map_groups(lambda grp: {"k": grp[0]["k"], "total": sum(r["v"] for r in grp)})
        .take_all()
    )
    got = {r["k"]: r["total"] for r in out}
    assert got == {
        0: sum(i for i in range(30) if i % 3 == 0),
        1: sum(i for i in range(30) if i % 3 == 1),
        2: sum(i for i in range(30) if i % 3 == 2),
    }


def test_join_inner_and_left(rt):
    left = rtd.from_items(
        [{"id": i, "a": i * 10} for i in range(8)], parallelism=3
    )
    right = rtd.from_items(
        [{"id": i, "b": i * 100} for i in range(4, 12)], parallelism=3
    )
    inner = left.join(right, on="id").take_all()
    assert sorted(r["id"] for r in inner) == [4, 5, 6, 7]
    for r in inner:
        assert r["a"] == r["id"] * 10 and r["b"] == r["id"] * 100
    lf = left.join(right, on="id", how="left").take_all()
    assert sorted(r["id"] for r in lf) == list(range(8))
    assert sum(1 for r in lf if "b" not in r) == 4


def test_random_shuffle_is_exact_permutation(rt):
    n = 400
    ds = rtd.range(n, parallelism=8).random_shuffle(seed=11)
    out = [r["id"] for r in ds.take_all()]
    assert sorted(out) == list(range(n))
    assert out != list(range(n))  # actually shuffled
    # deterministic under the same seed
    out2 = [r["id"] for r in rtd.range(n, parallelism=8)
            .random_shuffle(seed=11).take_all()]
    assert out == out2


def test_write_and_read_roundtrip(rt, tmp_path):
    """write_json / write_csv / write_numpy produce one file per block
    via distributed tasks; reading them back restores the rows
    (reference Dataset.write_* datasink parity)."""
    from ray_tpu import data as rd

    ds = rd.range(100, parallelism=4).map(
        lambda r: {"id": r["id"], "sq": r["id"] * r["id"]}
    )

    out_json = ds.write_json(str(tmp_path / "j"))
    assert len(out_json) == 4 and all(p.endswith(".jsonl") for p in out_json)
    back = rd.read_json([str(tmp_path / "j" / "*.jsonl")])
    rows = sorted(back.take_all(), key=lambda r: r["id"])
    assert len(rows) == 100 and rows[7] == {"id": 7, "sq": 49}

    out_csv = ds.write_csv(str(tmp_path / "c"))
    assert len(out_csv) == 4
    back_csv = rd.read_csv([str(tmp_path / "c" / "*.csv")])
    rows_csv = sorted(
        back_csv.take_all(), key=lambda r: int(r["id"])
    )
    assert len(rows_csv) == 100 and int(rows_csv[7]["sq"]) == 49

    out_npz = ds.write_numpy(str(tmp_path / "n"))
    assert len(out_npz) == 4
    import numpy as np

    total = sum(
        len(np.load(p)["id"]) for p in out_npz
    )
    assert total == 100


def test_streaming_split_concurrent_consumers(rt):
    """Two consumers drain ONE streaming execution concurrently and see
    disjoint, together-complete data (reference streaming_split)."""
    import threading

    from ray_tpu import data as rd

    ds = rd.range(64, parallelism=8).map(lambda r: {"v": r["id"]})
    splits = ds.streaming_split(2)
    seen = [[], []]

    def consume(i):
        for batch in splits[i].iter_batches(batch_size=None):
            seen[i].extend(int(v) for v in batch["v"])

    threads = [
        threading.Thread(target=consume, args=(i,)) for i in (0, 1)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert seen[0] and seen[1]  # both consumers got data
    assert not (set(seen[0]) & set(seen[1]))  # disjoint
    assert sorted(seen[0] + seen[1]) == list(range(64))  # complete
