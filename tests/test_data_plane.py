"""Object-transfer data plane (node_agent._data_loop + worker
_pull_via_data_plane; native/src/store_core.cpp pumps): whole segments
stream over a raw TCP port via sendfile instead of chunked RPC pulls.
Parity role: the reference object manager's dedicated data port
(src/ray/object_manager/object_manager.h).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import worker as worker_mod


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _segment_of(ref):
    w = worker_mod.global_worker()
    stored = w.memory_store.try_get(ref.id)
    assert hasattr(stored, "path"), "object did not land in plasma"
    return w, stored


def test_stream_matches_segment(rt):
    payload = np.random.default_rng(0).integers(
        0, 255, size=6 * 1024 * 1024, dtype=np.uint8
    )
    ref = rt.put(payload)
    w, stored = _segment_of(ref)
    buf = bytearray(stored.size)
    assert w._pull_via_data_plane(
        stored.path, stored.size, stored.agent_address, buf
    ), "data plane refused a healthy segment"
    with open(stored.path, "rb") as f:
        assert bytes(buf) == f.read(), "streamed bytes differ from segment"


def test_fallback_when_data_port_unreachable(rt):
    payload = np.arange(512 * 1024, dtype=np.int32)
    ref = rt.put(payload)
    w, stored = _segment_of(ref)
    # poison the cached port: the pull must fall back to chunked RPC and
    # still return correct bytes
    import time as _t
    w._data_ports[stored.agent_address] = (1, _t.monotonic())  # nothing listens on port 1
    try:
        view = w._pull_remote_segment(
            stored.path, stored.size, stored.agent_address
        )
        with open(stored.path, "rb") as f:
            assert bytes(view) == f.read()
    finally:
        w._data_ports.pop(stored.agent_address, None)


def test_lost_segment_reported(rt):
    from ray_tpu.core.exceptions import ObjectLostError

    ref = rt.put(np.zeros(1024 * 1024, dtype=np.uint8))
    w, stored = _segment_of(ref)
    bogus = stored.path.rsplit("_", 1)[0] + "_" + "0" * len(
        stored.path.rsplit("_", 1)[1]
    )
    with pytest.raises(ObjectLostError):
        w._pull_via_data_plane(bogus, stored.size, stored.agent_address,
                               bytearray(stored.size))


def test_xxh64_reference_vectors():
    """Native xxHash64 against the published reference vectors."""
    from ray_tpu import native

    lib = native.store_lib()
    if lib is None:
        pytest.skip("no native toolchain")
    # XXH64 test vectors (public spec)
    assert lib.rt_xxh64(b"", 0, 0) == 0xEF46DB3751D8E999
    assert lib.rt_xxh64(b"a", 1, 0) == 0xD24EC4F1A98C6E5B
    assert lib.rt_xxh64(b"abc", 3, 0) == 0x44BC2CF5AD770999
    data = bytes(range(101))
    assert lib.rt_xxh64(data, len(data), 0) == lib.rt_xxh64(data, len(data), 0)