"""Ring attention vs full reference attention on the CPU mesh."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh(cpu_mesh_devices):
    from ray_tpu.parallel import MeshConfig, build_mesh

    return build_mesh(MeshConfig(dp=2, cp=4, tp=1))


def _rand_qkv(shape, dtype):
    import jax

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(mesh, causal):
    import jax.numpy as jnp

    from ray_tpu.ops.attention import _reference_attention
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    B, T, H, D = 2, 64, 4, 32
    q, k, v = _rand_qkv((B, T, H, D), jnp.float32)
    ref = _reference_attention(q, k, v, causal)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


def test_ring_gradients_match(mesh):
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import _reference_attention
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    B, T, H, D = 2, 64, 4, 32
    q, k, v = _rand_qkv((B, T, H, D), jnp.float32)

    g_ref = jax.grad(
        lambda q, k, v: (_reference_attention(q, k, v, True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ring = jax.grad(
        lambda q, k, v: (
            ring_attention_sharded(q, k, v, mesh, causal=True) ** 2
        ).sum().astype(jnp.float32),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_multiblock(cpu_mesh_devices, causal, monkeypatch):
    """T > block size: exercises the K-blocked online-softmax accumulation
    across multiple (iq, ik) tiles, incl. causal tile skipping."""
    import jax
    import jax.numpy as jnp

    # force 4x4 tiles (default bk would cover T in one block -> one-shot path)
    monkeypatch.setenv("RT_FLASH_BQ", "256")
    monkeypatch.setenv("RT_FLASH_BK", "256")

    from ray_tpu.ops.attention import _reference_attention
    from ray_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 1, 1024, 1, 64  # 2x2 tile grid at block 512
    q, k, v = _rand_qkv((B, T, H, D), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(_reference_attention(q, k, v, causal)),
        np.asarray(flash_attention(q, k, v, causal)),
        rtol=1e-5, atol=1e-5,
    )
    g1 = jax.grad(
        lambda q, k, v: (_reference_attention(q, k, v, causal) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, causal) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_attention_cpu_interpret(cpu_mesh_devices):
    """Pallas flash kernel (interpret mode) vs reference, fwd + bwd."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.ops.attention import _reference_attention
    from ray_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 2, 128, 2, 64
    q, k, v = _rand_qkv((B, T, H, D), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(_reference_attention(q, k, v, True)),
        np.asarray(flash_attention(q, k, v, True)),
        rtol=1e-5, atol=1e-5,
    )
    g1 = jax.grad(
        lambda q, k, v: (_reference_attention(q, k, v, True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_einsum_oracle_matches(mesh, causal):
    """The einsum block-math variant stays as a numerics oracle."""
    import jax.numpy as jnp

    from ray_tpu.ops.attention import _reference_attention
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    B, T, H, D = 2, 64, 4, 32
    q, k, v = _rand_qkv((B, T, H, D), jnp.float32)
    ref = _reference_attention(q, k, v, causal)
    out = ring_attention_sharded(
        q, k, v, mesh, causal=causal, block_impl="einsum"
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )
