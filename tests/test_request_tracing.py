"""End-to-end request tracing + serving/pipeline SLO metrics (tier-1):
one streaming OpenAI request against a 4-replica deployment must show up
in ``state.timeline()`` as a single cross-pid flow whose
proxy/router/replica/engine spans share one trace id, populate the
TTFT / inter-token-latency histograms, and roll up into a
``state.request_summary()`` row; a compiled-pipeline step must stamp
per-stage fwd/bwd/idle slices whose measured bubble fraction separates
1F1B from GPipe at equal microbatches."""

import http.client
import json
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve, state
from ray_tpu.observability import tracing

MODEL = "tiny"
DEPLOYMENT = "traced-llm"


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=8)
    serve.start(http_port=0)
    yield ray_tpu
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def front(rt):
    """4-replica OpenAI deployment + the proxy address serving it."""
    from ray_tpu.serve import llm as serve_llm

    serve_llm.deploy(
        {MODEL: serve_llm.LLMConfig(model_id="gpt2-tiny", max_batch_size=4)},
        name=DEPLOYMENT, num_replicas=4, route_prefix="/v1",
    )
    deadline = time.monotonic() + 60
    addrs = []
    while time.monotonic() < deadline and not addrs:
        addrs = serve.proxy_addresses()
        time.sleep(0.2)
    assert addrs, "no HTTP proxy came up"
    yield addrs[0]
    serve.delete(DEPLOYMENT)


def _stream_chat(addr, body, headers=None, timeout=180):
    """POST a stream=true chat request; returns (status, sse payloads)."""
    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request(
            "POST", "/v1/chat/completions", body=json.dumps(body),
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        raw = resp.read().decode()
        events = [
            b[len("data: "):] for b in raw.split("\n\n") if b.strip()
        ]
        return resp.status, events
    finally:
        conn.close()


def _request_slices(trace, trace_id):
    return [
        ev for ev in trace
        if ev.get("cat") == "request" and ev.get("ph") == "X"
        and ev["args"].get("trace_id") == trace_id
    ]


def test_streaming_request_joins_one_trace(front):
    """The curl-shaped acceptance request: one SSE chat completion with a
    client-supplied x-rt-trace-id shows up as ONE joined flow — proxy,
    router, replica, and engine spans all carrying that id — with the
    TTFT/ITL/KV series populated and a request_summary row."""
    addr = front
    tid = "feedfacecafe0001"
    st, events = _stream_chat(addr, {
        "model": MODEL, "max_tokens": 8, "temperature": 0, "user": "alice",
        "stream": True,
        "messages": [{"role": "user", "content": "trace me"}],
    }, headers={tracing.TRACE_HEADER: tid})
    assert st == 200 and events[-1] == "[DONE]"

    # span collection is asynchronous across pids (the proxy stamps in
    # the stream generator's finally): poll until the flow is complete
    deadline = time.monotonic() + 30
    spans = []
    while time.monotonic() < deadline:
        spans = _request_slices(state.timeline(), tid)
        comps = {ev["name"].split(":")[0] for ev in spans}
        if {"proxy", "router", "replica", "engine"} <= comps:
            break
        time.sleep(0.3)
    comps = {ev["name"].split(":")[0] for ev in spans}
    assert {"proxy", "router", "replica", "engine"} <= comps, spans
    # component slices name their deployment (the engine leg reports the
    # model it decoded for)
    assert any(ev["name"] == f"proxy:{DEPLOYMENT}" for ev in spans)
    assert any(ev["name"] == "engine:gpt2-tiny" for ev in spans)
    # proxy and router share one process/clock: proxy opens first and
    # its end-to-end span covers the router's routing span
    proxy = next(ev for ev in spans if ev["name"].startswith("proxy:"))
    router = next(ev for ev in spans if ev["name"].startswith("router:"))
    assert proxy["ts"] <= router["ts"]
    assert proxy["ts"] + proxy["dur"] >= router["ts"] + router["dur"]

    # the flow join: one chain per trace id, start + terminator present,
    # one step per span, the terminator bound to its enclosing slice
    flow = [
        ev for ev in state.timeline()
        if ev.get("cat") == "request_flow" and ev.get("id") == tid
    ]
    assert len(flow) == len(_request_slices(state.timeline(), tid))
    phases = [ev["ph"] for ev in sorted(flow, key=lambda e: e["ts"])]
    assert phases[0] == "s" and phases[-1] == "f"
    assert all(p == "t" for p in phases[1:-1])
    assert next(ev for ev in flow if ev["ph"] == "f")["bp"] == "e"


def test_llm_serving_metrics_populated(front):
    """After traffic, the LLM SLO series are non-empty cluster-wide:
    TTFT and inter-token histograms counted, tokens counter >= the
    request budget, KV-occupancy and queue gauges published."""
    addr = front
    st, _ = _stream_chat(addr, {
        "model": MODEL, "max_tokens": 8, "temperature": 0, "user": "bob",
        "stream": True,
        "messages": [{"role": "user", "content": "measure me"}],
    })
    assert st == 200
    deadline = time.monotonic() + 30
    mx = {}
    while time.monotonic() < deadline:
        mx = state.cluster_metrics()
        ttft = mx.get("rt_serve_ttft_s", {}).get("series", {})
        itl = mx.get("rt_serve_inter_token_s", {}).get("series", {})
        if (
            any(s["count"] for s in ttft.values())
            and any(s["count"] for s in itl.values())
        ):
            break
        time.sleep(0.3)
    ttft = mx["rt_serve_ttft_s"]
    assert any(s["count"] >= 1 for s in ttft["series"].values())
    # the histogram keeps its bucket detail across the merge (identical
    # boundaries in every engine process)
    assert ttft["boundaries"], ttft
    assert any(
        s["count"] >= 1
        for s in mx["rt_serve_inter_token_s"]["series"].values()
    )
    tokens = mx.get("rt_serve_tokens_generated_total", {}).get("series", {})
    assert sum(tokens.values()) >= 8
    assert mx.get("rt_serve_kv_slots_occupied", {}).get("series"), mx.keys()
    assert mx.get("rt_serve_queued_requests", {}).get("series")
    fill = mx.get("rt_serve_batch_fill", {}).get("series", {})
    assert any(s["count"] >= 1 for s in fill.values())


def test_request_summary_rolls_up_percentiles(front):
    """state.request_summary() turns the request spans into a
    per-deployment row: e2e (proxy), queue (router), exec (replica)
    percentile splits, each covering the traffic sent so far."""
    addr = front
    st, _ = _stream_chat(addr, {
        "model": MODEL, "max_tokens": 4, "temperature": 0, "user": "carol",
        "stream": True,
        "messages": [{"role": "user", "content": "summarize me"}],
    })
    assert st == 200
    deadline = time.monotonic() + 30
    entry = None
    while time.monotonic() < deadline:
        summary = state.request_summary()
        entry = summary["deployments"].get(DEPLOYMENT)
        if entry and entry["count"] >= 1 and "exec_s" in entry:
            break
        time.sleep(0.3)
    assert entry and entry["count"] >= 1, entry
    for split in ("e2e_s", "queue_s", "exec_s"):
        assert split in entry, (split, entry)
        for pct in ("p50", "p95", "p99", "mean", "max"):
            assert entry[split][pct] >= 0.0
    # the proxy span wraps replica execution: e2e can't be faster
    assert entry["e2e_s"]["max"] >= entry["exec_s"]["p50"]


def test_trace_minted_when_client_sends_none(front):
    """Without an x-rt-trace-id header the proxy mints one, and the
    downstream legs still join on it."""
    addr = front
    st, _ = _stream_chat(addr, {
        "model": MODEL, "max_tokens": 2, "temperature": 0, "user": "dave",
        "stream": True,
        "messages": [{"role": "user", "content": "mint me"}],
    })
    assert st == 200
    deadline = time.monotonic() + 30
    joined = set()
    while time.monotonic() < deadline and not joined:
        by_tid = {}
        for ev in state.timeline():
            if ev.get("cat") == "request" and ev.get("ph") == "X":
                by_tid.setdefault(ev["args"]["trace_id"], set()).add(
                    ev["name"].split(":")[0]
                )
        joined = {
            tid for tid, comps in by_tid.items()
            if {"proxy", "router", "replica"} <= comps
        }
        time.sleep(0.3)
    assert joined, by_tid
    # minted ids follow new_trace_id()'s shape
    assert any(len(t) == 16 and int(t, 16) >= 0 for t in joined)


# ---------------------------------------------------------------------------
# compiled-pipeline slices + bubble fraction: 1F1B vs GPipe
# ---------------------------------------------------------------------------


def _weighted_stages():
    """Two stages with deliberate, sleep-dominated costs: stage0's
    FORWARD is slow (~30ms) and stage1's BACKWARD is slow (~20ms, via a
    custom_vjp sleep — pullbacks are cached at forward time, so a sleep
    in the primal would never reach the backward op). GPipe can only run
    stage1's expensive backwards after the full forward flush, leaving
    stage0 idle for every one of them; 1F1B overlaps them with stage0's
    remaining forwards, so stage0's measured input-wait (the bubble) is
    structurally smaller."""
    rng = np.random.default_rng(7)
    W1 = rng.normal(size=(8, 16)).astype(np.float32) * 0.3
    W2 = rng.normal(size=(16, 4)).astype(np.float32) * 0.3
    X = rng.normal(size=(32, 8)).astype(np.float32)
    Y = rng.normal(size=(32, 4)).astype(np.float32)

    def stage1(params, x):
        import time as _t

        import jax.numpy as jnp

        _t.sleep(0.03)
        return jnp.tanh(x @ params["w"])

    def stage2(params, h):
        import jax

        @jax.custom_vjp
        def slow_grad_ident(x):
            return x

        def vjp_fwd(x):
            return x, None

        def vjp_bwd(_res, g):
            import time as _t

            _t.sleep(0.02)
            return (g,)

        slow_grad_ident.defvjp(vjp_fwd, vjp_bwd)
        return slow_grad_ident(h @ params["w"])

    def loss_fn(pred, target):
        import jax.numpy as jnp

        return jnp.mean((pred - target) ** 2)

    return W1, W2, X, Y, stage1, stage2, loss_fn


def _stage_events(kind=None, schedule=None, stage=None):
    out = []
    for e in state.task_events():
        if e.get("type") != "pipeline":
            continue
        if kind is not None and e["kind"] != kind:
            continue
        if schedule is not None and e.get("schedule") != schedule:
            continue
        if stage is not None and e["stage"] != stage:
            continue
        out.append(e)
    return out


def test_pipeline_slices_and_bubble_1f1b_beats_gpipe(rt):
    """A compiled step stamps per-stage fwd/bwd slices plus a per-step
    summary carrying bubble_frac, and at equal microbatches the measured
    stage-0 bubble of 1F1B is below GPipe's — the two schedules are
    comparable in one timeline."""
    from ray_tpu.parallel.pipeline import Pipeline

    W1, W2, X, Y, stage1, stage2, loss_fn = _weighted_stages()
    n_mb, n_steps = 4, 3
    bubbles = {}
    for sched in ("gpipe", "1f1b"):
        pipe = Pipeline([stage1, stage2], [{"w": W1}, {"w": W2}], loss_fn)
        cp = pipe.compile(schedule=sched, step_timeout_s=60.0)
        try:
            for _ in range(n_steps):
                cp.train_step(
                    list(np.split(X, n_mb)), list(np.split(Y, n_mb)), lr=0.1
                )
            # collect BEFORE teardown: the slices live in the stage
            # actors' worker event rings
            fwd = _stage_events(kind="fwd", schedule=sched, stage=0)
            bwd = _stage_events(kind="bwd", schedule=sched, stage=0)
            steps = _stage_events(kind="step", schedule=sched, stage=0)
            mx = state.cluster_metrics()
        finally:
            cp.teardown(timeout_s=30.0)
            pipe.shutdown()
        # every microbatch of every step left a slice, stamped with its
        # step/microbatch coordinates
        assert len(fwd) >= n_mb * n_steps, (sched, len(fwd))
        assert len(bwd) >= n_mb * n_steps, (sched, len(bwd))
        assert {e["microbatch"] for e in fwd} == set(range(n_mb))
        assert all(e["dur_us"] > 0 for e in fwd + bwd)
        assert len(steps) >= n_steps
        for e in steps:
            assert 0.0 <= e["bubble_frac"] < 1.0
            assert e["n_microbatches"] == n_mb
        # compare on warm steps only: step 0 carries one-time jax
        # dispatch/compile costs that are schedule-independent noise
        warm = [e["bubble_frac"] for e in steps if e["step"] >= 1]
        bubbles[sched] = sum(warm) / len(warm)
        # the fwd slices are sleep-dominated: stage0's forward floor
        assert max(e["dur_us"] for e in fwd) >= 25_000
        # the cluster-wide metric carries this run's schedule label
        # (snapshotted before teardown: the series live in the stage
        # actors' processes)
        bf = mx.get("rt_pipeline_bubble_fraction", {})
        scheds = {
            dict(zip(bf.get("tag_keys", ()), k)).get("schedule")
            for k in bf.get("series", {})
        }
        assert sched in scheds, (sched, scheds)
        busy = mx.get("rt_pipeline_stage_busy_s", {}).get("series", {})
        assert any(s["count"] >= 1 for s in busy.values())
    # the observability acceptance inequality: same work, same
    # microbatches — 1F1B's interleaving shrinks stage-0's input wait
    assert bubbles["1f1b"] < bubbles["gpipe"], bubbles
