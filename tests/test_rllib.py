"""RLlib-lite tests (parity model: rllib PPO learning tests on
CartPole)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def test_cartpole_env_contract():
    from ray_tpu.rllib import CartPole

    env = CartPole()
    obs, info = env.reset(seed=0)
    assert obs.shape == (4,)
    total = 0.0
    done = False
    steps = 0
    while not done and steps < 600:
        obs, r, term, trunc, _ = env.step(steps % 2)
        total += r
        done = term or trunc
        steps += 1
    assert 1 <= steps <= 500
    # alternating actions balance poorly: episode ends early
    assert steps < 500


def test_ppo_learns_cartpole(rt):
    """PPO on CartPole: mean episode return must improve substantially
    over a handful of iterations (random policy ~= 20)."""
    from ray_tpu.rllib import PPOConfig

    algo = PPOConfig(num_env_runners=2, seed=3).build()
    try:
        first = None
        best = 0.0
        for _ in range(15):
            result = algo.train()
            ret = result["episode_return_mean"]
            if ret is not None:
                if first is None:
                    first = ret
                best = max(best, ret)
        assert first is not None
        assert best >= max(60.0, 2 * first), (
            f"PPO did not learn: first={first}, best={best}"
        )
        # the learned greedy policy balances much longer than random
        from ray_tpu.rllib import CartPole

        env = CartPole()
        obs, _ = env.reset(seed=42)
        steps = 0
        done = False
        while not done and steps < 500:
            obs, _, term, trunc, _ = env.step(algo.compute_action(obs))
            done = term or trunc
            steps += 1
        assert steps >= 100, f"greedy policy survived only {steps} steps"
    finally:
        algo.stop()
