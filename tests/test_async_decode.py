"""Async decode pipeline (RT_SERVE_ASYNC_DECODE): the engine dispatches
decode chunk N+1 from chunk N's device-resident outputs before
materializing chunk N's tokens, so host bookkeeping (fan-out, SSE puts,
metrics, reaping, admission) overlaps device compute.

Pins the PR's contracts:
  * temp=0 generations are BITWISE identical async-on vs async-off
    (unary and SSE, paged and slot engines) — the lookahead reorders
    WHEN the host sees tokens, never which tokens the device samples;
  * cancellation landing while a lookahead chunk is in flight drops
    that chunk's tokens on the host and returns every page (deferred
    one step, so the in-flight chunk never scatters into freed pages);
  * an engine exception mid-lookahead fails the in-flight requests
    (fail_inflight) without hanging callers or leaking pool pages;
  * an idle engine admits a fresh arrival immediately — the old
    wait-then-clear order could eat the wakeup and add a 0.5 s TTFT
    mode (the lost-wakeup race).
"""

import threading
import time

import numpy as np
import pytest


def _mk(paged: bool, async_on: bool, batch: int = 4):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from ray_tpu.serve.llm import LLMConfig, LLMServer

    return LLMServer(LLMConfig(
        model_id="gpt2-tiny", max_batch_size=batch, paged_kv=paged,
        async_decode=async_on,
    ))


@pytest.fixture(scope="module")
def engines():
    """All four engine variants, torn down together: (paged, async) ->
    server. Module-scoped — each holds a tiny CPU model."""
    servers = {
        (paged, async_on): _mk(paged, async_on)
        for paged in (True, False)
        for async_on in (True, False)
    }
    yield servers
    for srv in servers.values():
        srv._stop.set()
        srv._work.set()


def _req(prompt, max_new=24, **extra):
    return {"prompt_tokens": prompt, "max_new_tokens": max_new,
            "temperature": 0.0, **extra}


# ---------------------------------------------------------------------------
# parity: async on/off is invisible at temp=0
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("paged", [True, False],
                         ids=["paged", "slot"])
def test_async_vs_sync_unary_bitwise(engines, paged):
    """The lookahead must not change a single sampled token: same
    step_no/rng discipline, same chunk sizes, same finish budgets —
    short, block-spanning, and window-filling prompts."""
    rng = np.random.RandomState(41)
    for n in (10, 64, 127):
        prompt = [int(t) for t in rng.randint(0, 256, n)]
        a = engines[(paged, True)](_req(prompt))["tokens"]
        s = engines[(paged, False)](_req(prompt))["tokens"]
        assert a == s, f"async != sync (paged={paged}, prompt len {n})"


@pytest.mark.parametrize("paged", [True, False],
                         ids=["paged", "slot"])
def test_async_vs_sync_sse_stream_bitwise(engines, paged):
    """SSE rides the pipeline: the streamed token sequence (fan-out now
    happens one chunk AFTER dispatch in async mode) matches the sync
    stream and the unary result exactly, and the stream terminates."""
    rng = np.random.RandomState(42)
    prompt = [int(t) for t in rng.randint(0, 256, 33)]

    def collect(srv):
        return [ev["token"] for ev in srv(_req(prompt, stream=True))]

    a = collect(engines[(paged, True)])
    s = collect(engines[(paged, False)])
    u = engines[(paged, True)](_req(prompt))["tokens"]
    assert a == s == u
    assert len(a) == 24


# ---------------------------------------------------------------------------
# mid-lookahead cancellation: dropped tokens, no page leak
# ---------------------------------------------------------------------------


def test_mid_lookahead_cancel_returns_pages(engines):
    """Closing a stream while a lookahead chunk is in flight marks the
    row dropped: its remaining tokens never reach the queue, its pages
    free via the deferred path once the chunk harvests, and occupancy
    returns to idle — no rt_serve_kv_pages_occupied leak."""
    srv = engines[(True, True)]
    pool = srv._prefix_pool
    idle_occ = pool.stats()["pages_occupied"]
    gen = srv(_req([7] * 40, max_new=100, stream=True))
    got = [next(gen)["token"] for _ in range(3)]
    assert len(got) == 3
    gen.close()  # client disconnect mid-stream, lookahead in flight
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if (
            srv.batch_stats()["occupied"] == 0
            and pool.stats()["pages_occupied"] <= idle_occ
        ):
            break
        time.sleep(0.05)
    assert srv.batch_stats()["occupied"] == 0
    assert pool.stats()["pages_occupied"] <= idle_occ, pool.stats()
    # the engine keeps serving after the reap
    assert len(srv(_req([7] * 40, max_new=4))["tokens"]) == 4


# ---------------------------------------------------------------------------
# mid-lookahead exception: fail_inflight, reclaim, recover
# ---------------------------------------------------------------------------


def test_mid_lookahead_exception_fails_and_recovers(monkeypatch):
    """A decode fault while a chunk is in flight must fail the caller
    promptly (fail_inflight covers rows whose finish was scheduled at
    dispatch but never harvested), reclaim every page through the
    deferred-free + pool-reset path, and leave the engine serving."""
    from ray_tpu.models import gpt2_decode

    srv = _mk(paged=True, async_on=True)
    try:
        srv(_req([3] * 20, max_new=4))  # warm the compile caches
        pool = srv._prefix_pool
        idle_occ = pool.stats()["pages_occupied"]

        real_multi = gpt2_decode.decode_multi_paged
        real_single = gpt2_decode.decode_paged_and_sample
        calls = {"n": 0}

        def poison(real):
            def wrapped(*a, **kw):
                calls["n"] += 1
                if calls["n"] >= 2:  # first chunk dispatches clean:
                    # the fault lands with a lookahead in flight
                    raise RuntimeError("injected decode fault")
                return real(*a, **kw)
            return wrapped

        monkeypatch.setattr(
            gpt2_decode, "decode_multi_paged", poison(real_multi)
        )
        monkeypatch.setattr(
            gpt2_decode, "decode_paged_and_sample", poison(real_single)
        )
        with pytest.raises(RuntimeError, match="injected decode fault"):
            srv(_req([3] * 20, max_new=16))
        monkeypatch.setattr(gpt2_decode, "decode_multi_paged", real_multi)
        monkeypatch.setattr(
            gpt2_decode, "decode_paged_and_sample", real_single
        )
        # the rebuild resets the pool: occupancy back to idle, and the
        # engine answers the next request as if nothing happened
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if (
                srv.batch_stats()["occupied"] == 0
                and pool.stats()["pages_occupied"] <= idle_occ
            ):
                break
            time.sleep(0.05)
        assert pool.stats()["pages_occupied"] <= idle_occ, pool.stats()
        assert len(srv(_req([3] * 20, max_new=4))["tokens"]) == 4
    finally:
        srv._stop.set()
        srv._work.set()


# ---------------------------------------------------------------------------
# lost-wakeup race: idle-arrival TTFT has no 0.5 s mode
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("paged", [True, False],
                         ids=["paged", "slot"])
def test_idle_arrival_ttft_no_half_second_mode(engines, paged):
    """The engine consumes the wake flag BEFORE scanning the queue, so
    a request arriving while it sleeps in _work.wait(0.5) always wakes
    it immediately. The old wait-then-clear order could eat the set()
    and park a fresh arrival for the full 500 ms timeout."""
    srv = engines[(paged, True)]
    prompt = [11] * 12
    srv(_req(prompt, max_new=2))  # warm compile caches
    lat = []
    for _ in range(6):
        time.sleep(0.12)  # let the engine reach the idle wait
        t0 = time.monotonic()
        srv(_req(prompt, max_new=2))
        lat.append(time.monotonic() - t0)
    assert max(lat) < 0.45, (
        f"idle-arrival TTFT shows a ~0.5s mode: {sorted(lat)}"
    )


def test_concurrent_streams_all_complete(engines):
    """Batched async decode under churn: several concurrent streams of
    unequal lengths all run to completion with the right token counts
    (staggered finishes exercise retire-at-dispatch + deferred frees)."""
    srv = engines[(True, True)]
    out = {}

    def run(tag, n, m):
        out[tag] = [
            ev["token"]
            for ev in srv(_req([tag] * n, max_new=m, stream=True))
        ]

    ts = [
        threading.Thread(target=run, args=(17 + j, 10 + 7 * j, 6 + 5 * j))
        for j in range(3)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert sorted(len(v) for v in out.values()) == [6, 11, 16]
