"""JaxBackend TPU-mode wiring (VERDICT round-3 weak #10): the use_tpu
branch of _bootstrap_backend must produce a REAL multi-process
jax.distributed bring-up — coordinator rendezvous through the control
store, RT_XLA_* env on every rank, jax.distributed.initialize joining
all ranks into one runtime. Runs on the CPU backend (tpu_chips_per_worker
=0 keeps workers on the cpu worker pool), which exercises the identical
code path the TPU pool uses (parity: reference train/v2/jax/config.py:31
_setup_jax_distributed_environment).
"""

import json

import pytest

import ray_tpu
from ray_tpu.train import DataParallelTrainer, ScalingConfig


@pytest.fixture(scope="module")
def rt():
    ray_tpu.init(num_cpus=4)
    yield ray_tpu
    ray_tpu.shutdown()


def _xla_train_fn(config):
    import os

    import jax

    import ray_tpu.train as train

    ctx = train.get_context()
    # the backend must have wired the group env BEFORE the train fn ran
    # (TrainWorker.run calls initialize_xla_group from it)
    assert os.environ["RT_XLA_GROUP"]
    assert int(os.environ["RT_XLA_WORLD"]) == ctx.get_world_size()
    assert int(os.environ["RT_XLA_RANK"]) == ctx.get_world_rank()
    train.report({
        "rank": ctx.get_world_rank(),
        "jax_process_index": jax.process_index(),
        "jax_process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
    })


def test_tpu_backend_brings_up_jax_distributed(rt, tmp_path):
    from ray_tpu.train import RunConfig

    trainer = DataParallelTrainer(
        _xla_train_fn,
        train_loop_config={},
        # use_tpu drives the RT_XLA_* backend branch; 0 chips per worker
        # keeps the resource demand CPU-only so the test runs on the cpu
        # worker pool with JAX_PLATFORMS=cpu
        scaling_config=ScalingConfig(
            num_workers=2, use_tpu=True, tpu_chips_per_worker=0,
        ),
        run_config=RunConfig(name="xla_backend", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    m = result.metrics
    assert m["jax_process_count"] == 2, m
    assert m["global_devices"] == 2 * m["local_devices"], m


def test_multislice_env_includes_megascale(rt):
    """xla_coordinator_env must add the MEGASCALE multislice variables
    (parity: reference util/tpu.py:198 + train/v2/jax/config.py:113)."""
    from ray_tpu.collective.xla_group import xla_coordinator_env

    env0 = xla_coordinator_env(
        "ms_group", rank=0, world_size=4, num_slices=2, slice_id=0
    )
    env1 = xla_coordinator_env(
        "ms_group", rank=1, world_size=4, num_slices=2, slice_id=1
    )
    assert env0["JAX_COORDINATOR_ADDRESS"] == env1["JAX_COORDINATOR_ADDRESS"]
    for e, sid in ((env0, 0), (env1, 1)):
        assert e["MEGASCALE_NUM_SLICES"] == "2"
        assert e["MEGASCALE_SLICE_ID"] == str(sid)
        assert "MEGASCALE_COORDINATOR_ADDRESS" in e
