"""Head fault tolerance (core/ha/): WAL replay determinism and the
end-to-end head-kill/restart failover path.

Parity rationale: the reference's GCS FT tests kill the gcs_server
process under Redis persistence and assert raylets reconnect and
actors/PGs survive; here the durable store is the snapshot+WAL file
backend and the cluster re-attaches through the heartbeat/reattach
protocol."""

import json
import time

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster
from ray_tpu.core.control_store import ControlStore
from ray_tpu.utils.config import config
from ray_tpu.utils.rpc import RpcClient

ACTOR_ID = "c" * 32
PG_ID = "d" * 28


def _canon(o):
    """Canonical (object-identity-independent) form of the durable
    tables. Insertion order is preserved — it is part of replayed state —
    while pickle's memo-based sharing of equal leaf objects is not."""
    if isinstance(o, dict):
        return [[repr(k), _canon(v)] for k, v in o.items()]
    if isinstance(o, (list, tuple)):
        return [_canon(v) for v in o]
    if isinstance(o, bytes):
        return "b:" + o.hex()
    return o


def _canonical_bytes(tables) -> bytes:
    return json.dumps(_canon(tables)).encode()


def _mutate_everything(client):
    """Touch every durable table, including delete/overwrite paths."""
    for i in range(5):
        client.call("kv_put", ns="fn", key=f"k{i}", value=b"v%d" % i)
    client.call("kv_put", ns="fn", key="k1", value=b"overwritten")
    client.call("kv_del", ns="fn", key="k3")
    client.call("kv_put", ns="other", key="a", value=b"1")
    client.call("kv_del_prefix", ns="other", prefix="")
    client.call("kv_put", ns="coll/run1", key="r0", value=b"volatile")
    job_id = client.call("register_job", driver_address="d:1", metadata={"u": 1})
    job2 = client.call("register_job", driver_address="d:2", metadata={})
    client.call("finish_job", job_id=job2)
    client.call("register_actor", spec={
        "actor_id": ACTOR_ID,
        "job_id": job_id,
        "name": "det-actor",
        "namespace": "ns1",
        "class_name": "Det",
        "resources": {"CPU": 1.0},
        "max_restarts": 3,
    })
    client.call(
        "create_placement_group",
        pg_id=PG_ID, bundles=[{"CPU": 1.0}, {"CPU": 1.0}],
        strategy="SPREAD", name="det-pg", job_id=job_id,
    )
    return job_id


def test_wal_replay_determinism(tmp_path):
    """Snapshot+WAL recovery rebuilds BYTE-IDENTICAL durable tables, for
    both a clean shutdown (final snapshot) and a crash (WAL tail replay
    over the initial snapshot)."""
    # crash leg: initial empty snapshot + every mutation replayed from WAL
    path = str(tmp_path / "crash.db")
    cs = ControlStore("sessA" + "0" * 26, persistence_path=path)
    cs.start()
    client = RpcClient(cs.address, name="det1")
    _mutate_everything(client)
    client.close()
    live = _canonical_bytes(cs._durable_state_snapshot())
    # simulate a crash: detach the durable log so stop() writes no final
    # snapshot — recovery then has only the WAL tail
    ha, cs._ha = cs._ha, None
    ha.backend.close()
    cs.stop()

    cs2 = ControlStore("sessB" + "0" * 26, persistence_path=path)
    cs2.start()
    try:
        restored = _canonical_bytes(cs2._durable_state_snapshot())
        assert restored == live
        assert cs2._ha.stats()["wal_replayed"] > 0  # replay actually ran
    finally:
        cs2.stop()

    # clean-stop leg: the same state arrives via the final snapshot
    cs3 = ControlStore("sessC" + "0" * 26, persistence_path=path)
    cs3.start()
    cs3.stop()
    cs4 = ControlStore("sessD" + "0" * 26, persistence_path=path)
    cs4.start()
    try:
        assert _canonical_bytes(cs4._durable_state_snapshot()) == live
        assert cs4._ha.stats()["wal_replayed"] == 0  # pure snapshot load
    finally:
        cs4.stop()


def test_wal_compaction(tmp_path):
    """Crossing the compaction threshold folds the WAL into a snapshot;
    recovery state is unchanged."""
    path = str(tmp_path / "compact.db")
    old = config.get("ha_wal_compact_entries")
    config.set("ha_wal_compact_entries", 10)
    try:
        cs = ControlStore("sessE" + "0" * 26, persistence_path=path)
        cs.start()
        client = RpcClient(cs.address, name="compact")
        for i in range(35):
            client.call("kv_put", ns="bulk", key=f"k{i}", value=b"x" * 64)
        client.close()
        live = _canonical_bytes(cs._durable_state_snapshot())
        stats = cs._ha.stats()
        assert stats["compactions"] >= 3
        assert stats["wal_since_snapshot"] < 10
        ha, cs._ha = cs._ha, None  # crash (WAL tail only, post-compaction)
        ha.backend.close()
        cs.stop()
        cs2 = ControlStore("sessF" + "0" * 26, persistence_path=path)
        cs2.start()
        try:
            assert _canonical_bytes(cs2._durable_state_snapshot()) == live
        finally:
            cs2.stop()
    finally:
        config.set("ha_wal_compact_entries", old)


def test_compaction_crash_between_snapshot_and_truncate(tmp_path):
    """A kill between the compaction snapshot's rename and the WAL reset
    must not double-apply ops on recovery: frames at or below the
    snapshot's folded seq are skipped."""
    from ray_tpu.core.ha.wal import SNAPSHOT_VERSION, FileBackend, HAState

    path = str(tmp_path / "torn.db")
    counter = {"n": 0}
    ha = HAState(FileBackend(path), compact_entries=1000)
    ha.recover()
    ha.start(lambda: dict(counter))
    for _ in range(5):
        ha.append("add", (1,), lambda: dict(counter))
        counter["n"] += 1
    # crash window: snapshot renamed into place, WAL NOT yet truncated
    ha.backend.write_snapshot({
        "version": SNAPSHOT_VERSION, "epoch": ha.epoch, "seq": ha.seq,
        "meta": {}, "tables": dict(counter),
    })
    ha.backend.close()

    ha2 = HAState(FileBackend(path))
    tables, records = ha2.recover()
    assert tables == {"n": 5}
    assert records == []  # every WAL frame was already folded in


def test_corrupt_snapshot_quarantined(tmp_path):
    """A present-but-unreadable snapshot must not be conflated with 'no
    snapshot': recovery quarantines the snapshot+WAL pair (evidence
    preserved) and starts from EMPTY state rather than replaying the
    post-compaction WAL tail onto nothing."""
    import os

    path = str(tmp_path / "c.db")
    cs = ControlStore("sessG" + "0" * 26, persistence_path=path)
    cs.start()
    client = RpcClient(cs.address, name="corrupt")
    client.call("kv_put", ns="x", key="k", value=b"v")
    client.close()
    cs.stop()
    with open(path, "wb") as f:
        f.write(b"not a pickle")

    cs2 = ControlStore("sessH" + "0" * 26, persistence_path=path)
    cs2.start()
    try:
        client = RpcClient(cs2.address, name="corrupt2")
        assert client.call("kv_get", ns="x", key="k") is None  # fresh start
        client.close()
        assert os.path.exists(path + ".corrupt")
    finally:
        cs2.stop()


def test_head_kill_restart_end_to_end(tmp_path):
    """Acceptance: with a running cluster (2 node agents, a named actor,
    an active PG, tasks in flight), kill -9 the head process and restart
    it on the same address + durable log. The cluster reconciles within
    the window, pre-failover refs still resolve, the named actor
    answers, in-flight and new tasks complete, and no duplicate
    actors/PGs exist."""
    old_window = config.get("ha_reconcile_window_s")
    config.set("ha_reconcile_window_s", 4.0)
    cluster = Cluster(
        external_head=True,
        persistence_path=str(tmp_path / "head.db"),
    )
    try:
        cluster.add_node(num_cpus=3)
        cluster.add_node(num_cpus=3)
        ray_tpu.init(address=cluster.address)

        @ray_tpu.remote
        def quick(x):
            return x * 2

        @ray_tpu.remote
        def slow(x):
            time.sleep(4.0)
            return x + 100

        @ray_tpu.remote(num_cpus=1, max_restarts=1)
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        pg = ray_tpu.placement_group(
            [{"CPU": 1.0}, {"CPU": 1.0}], strategy="SPREAD"
        )
        assert pg.wait(timeout_seconds=60)
        counter = Counter.options(name="survivor").remote()
        assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
        pre_ref = ray_tpu.put({"epoch": "before-failover"})
        assert ray_tpu.get([quick.remote(i) for i in range(6)],
                           timeout=60) == [i * 2 for i in range(6)]
        inflight = [slow.remote(i) for i in range(4)]  # outlive the bounce

        cluster.kill_head()
        time.sleep(1.0)
        cluster.restart_head()

        # tasks in flight across the bounce complete normally
        assert ray_tpu.get(inflight, timeout=120) == [100, 101, 102, 103]
        # pre-failover refs still resolve
        assert ray_tpu.get(pre_ref, timeout=60) == {"epoch": "before-failover"}

        # wait out reconciliation
        probe = RpcClient(cluster.address, name="probe")
        deadline = time.monotonic() + 60
        st = probe.call("ha_status", retryable=True)
        while time.monotonic() < deadline and st["recovering"]:
            time.sleep(0.25)
            st = probe.call("ha_status")
        assert not st["recovering"]
        assert st["epoch"] >= 1
        assert st["reattached_nodes"] >= 2

        # both nodes survived reconciliation (nobody GC'd or restarted)
        nodes = probe.call("get_nodes")
        assert len(nodes) == 2

        # the named actor survived in place and kept its state
        handle = ray_tpu.get_actor("survivor")
        assert ray_tpu.get(handle.incr.remote(), timeout=60) == 2
        actors = probe.call("list_actors")
        survivors = [
            a for a in actors
            if a["name"] == "survivor" and a["state"] == "ALIVE"
        ]
        assert len(survivors) == 1, actors
        assert all(a["num_restarts"] == 0 for a in survivors)

        # the PG survived with its bundles intact — and still takes work
        pgs = probe.call("list_placement_groups")
        assert len(pgs) == 1
        assert pgs[0]["state"] == "CREATED"
        assert len(pgs[0]["bundle_locations"]) == 2
        from ray_tpu.core.placement import PlacementGroupSchedulingStrategy

        strategy = PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=0
        )
        assert ray_tpu.get(
            quick.options(scheduling_strategy=strategy).remote(21),
            timeout=60,
        ) == 42

        # new work flows normally after failover
        assert ray_tpu.get([quick.remote(i) for i in range(6)],
                           timeout=60) == [i * 2 for i in range(6)]
        probe.close()
    finally:
        config.set("ha_reconcile_window_s", old_window)
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()
