"""Observability guard static check (tier-1): every metric update /
trace stamp in the package must sit behind the module-level kill switch
(`if core_metrics.ENABLED:` / `if tracing.ENABLED:`), and the checker
itself must keep catching each unguarded pattern."""

import os
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
sys.path.insert(0, TOOLS)
sys.path.insert(0, REPO)

from check_metric_guards import (  # noqa: E402
    check_source, iter_default_files, check_file,
)
from tools.rtlint import check_source as rtlint_check  # noqa: E402


def test_package_stamps_are_guarded():
    for path in iter_default_files(REPO):
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            src = f.read()
        findings = [
            f for f in rtlint_check(src, rel, pass_ids=["metric-guards"])
            if not f.suppressed
        ]
        assert not findings, "\n".join(f.format() for f in findings)


def test_legacy_shim_api_preserved():
    violations = check_source(
        "def route(dep):\n"
        "    core_metrics.serve_router_requests.inc()\n"
    )
    assert len(violations) == 1
    assert isinstance(violations[0], str)
    assert callable(check_file)


def _check(body: str):
    findings = rtlint_check(
        textwrap.dedent(body), pass_ids=["metric-guards"]
    )
    return [f.message for f in findings if not f.suppressed]


def test_flags_unguarded_counter_inc():
    violations = _check("""
        def route(dep):
            core_metrics.serve_router_requests.inc(tags={"deployment": dep})
    """)
    assert len(violations) == 1
    assert "core_metrics.ENABLED" in violations[0]


def test_flags_unguarded_emit_and_append():
    violations = _check("""
        def stamp(self, evt):
            tracing.emit(evt)
            self._append_task_event(evt)
    """)
    assert len(violations) == 2
    assert all("tracing.ENABLED" in v for v in violations)


def test_accepts_plain_guard():
    violations = _check("""
        def route(dep):
            if core_metrics.ENABLED:
                core_metrics.serve_router_requests.inc(
                    tags={"deployment": dep}
                )
    """)
    assert not violations, violations


def test_accepts_compound_and_mixed_guards():
    violations = _check("""
        def stamp(self, tid, occupancy):
            if tid and tracing.ENABLED:
                tracing.emit({"trace_id": tid})
            if core_metrics.ENABLED or tracing.ENABLED:
                if core_metrics.ENABLED:
                    core_metrics.serve_batch_fill.observe(occupancy)
                if tracing.ENABLED:
                    tracing.emit({"fill": occupancy})
    """)
    assert not violations, violations


def test_accepts_early_return_guard():
    violations = _check("""
        def publish(self):
            if not core_metrics.ENABLED:
                return
            core_metrics.object_store_used_bytes.set(self._used)
    """)
    assert not violations, violations


def test_wrong_module_guard_does_not_satisfy():
    violations = _check("""
        def stamp(evt):
            if core_metrics.ENABLED:
                tracing.emit(evt)
    """)
    assert len(violations) == 1
    assert "tracing.ENABLED" in violations[0]


def test_guard_does_not_leak_to_siblings():
    violations = _check("""
        def route(dep):
            if core_metrics.ENABLED:
                pass
            core_metrics.serve_router_requests.inc(tags={"deployment": dep})
    """)
    assert len(violations) == 1


def test_non_observability_calls_not_flagged():
    violations = _check("""
        def other(headers, s):
            headers.set("x", "y")
            s.observe(1.0)
            gauges.inc()
            tracing.now_us()
    """)
    assert not violations, violations


def test_honors_opt_out_mark():
    violations = _check("""
        def route(dep):
            core_metrics.serve_router_requests.inc()  # obs: unguarded
    """)
    assert not violations, violations


# -- profiler / forensics stamp helpers (PR 16 extension) -----------------

def test_flags_unguarded_forensics_stamp():
    violations = _check("""
        def watchdog(self, tid):
            forensics.stamp_stall(task_id=tid, name="t", elapsed_s=1.0,
                                  thread_ident=None, worker_address="a")
    """)
    assert len(violations) == 1
    assert "forensics.ENABLED" in violations[0]


def test_accepts_guarded_forensics_stamp():
    violations = _check("""
        def watchdog(self, tid):
            if forensics.ENABLED:
                forensics.stamp_stall(task_id=tid, name="t",
                                      elapsed_s=1.0, thread_ident=None,
                                      worker_address="a")
    """)
    assert not violations, violations


def test_profiler_stamp_requires_profiler_guard():
    # a tracing guard does not satisfy a profiler stamp site
    violations = _check("""
        def tick(self):
            if tracing.ENABLED:
                profiler.stamp_sample("rpc")
    """)
    assert len(violations) == 1
    assert "profiler.ENABLED" in violations[0]


def test_non_stamp_profiler_calls_not_flagged():
    violations = _check("""
        def report(self):
            profiler.capture(duration_s=1.0)
            forensics.all_thread_stacks()
            profiler.maybe_start_continuous()
    """)
    assert not violations, violations
