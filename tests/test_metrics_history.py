"""Metrics history store tests: reset-aware deltas, multi-resolution
ring folding, windowed percentiles against a direct reference, the
head-side sampler plane end-to-end, and the HTTP surface."""

import json
import threading
import time

import pytest

from ray_tpu.observability.history import (
    MetricsHistory,
    counter_delta,
    hist_delta,
)
from ray_tpu.utils.metrics import hist_quantile

# small tiers so every fold level is exercised in a handful of appends:
# 1-unit ring of 10, 5-unit ring of 6, 25-unit ring of 4
TIERS = ((1, 10), (5, 6), (25, 4))


def _gauge(value, ts=None):
    return {"g": {"kind": "gauge", "tag_keys": (), "series": {(): value}}}


def _counter(value, tags=("a",)):
    return {
        "c": {
            "kind": "counter",
            "tag_keys": ("k",),
            "series": {tags: value},
        }
    }


def _hist(count, total, buckets, bounds=(0.1, 1.0)):
    return {
        "h": {
            "kind": "histogram",
            "tag_keys": (),
            "boundaries": bounds,
            "series": {(): {"count": count, "sum": total,
                            "buckets": list(buckets)}},
        }
    }


# -- unit: reset-aware deltas ---------------------------------------------


def test_counter_delta_monotonic_reset_none():
    assert counter_delta(None, 5.0) == 5.0  # first scrape: all of it
    assert counter_delta(5.0, 8.0) == 3.0  # normal increase
    assert counter_delta(8.0, 8.0) == 0.0  # idle
    # decrease = process restart: the new cumulative IS the increase,
    # never a negative and never a silent zero
    assert counter_delta(8.0, 2.0) == 2.0
    assert counter_delta(2.0, 0.0) == 0.0


def test_hist_delta_reset_and_bucket_change():
    prev = {"count": 10, "sum": 5.0, "buckets": [6, 4]}
    cur = {"count": 13, "sum": 6.5, "buckets": [8, 5]}
    assert hist_delta(prev, cur) == (3.0, 1.5, [2, 1])
    # count went backwards -> restart: current cumulative is the delta
    reset = {"count": 2, "sum": 0.4, "buckets": [2, 0]}
    assert hist_delta(prev, reset) == (2.0, 0.4, [2, 0])
    # bucket arity changed (boundaries diverged mid-flight) -> rebaseline
    widened = {"count": 12, "sum": 6.0, "buckets": [6, 4, 2]}
    assert hist_delta(prev, widened) == (12.0, 6.0, [6, 4, 2])
    assert hist_delta(None, cur) == (13.0, 6.5, [8, 5])


# -- store: every tier, every kind ----------------------------------------


def test_gauge_folds_mean_through_every_tier():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    # 50 ticks of a ramp: values 0..49 at ts 0..49
    for t in range(50):
        h.record(float(t), _gauge(float(t)))
    q0 = h.query("g")
    assert q0["kind"] == "gauge" and q0["step_s"] == 1.0
    assert [p["value"] for p in q0["points"]] == [
        float(v) for v in range(40, 50)
    ]  # ring of 10 keeps the last 10
    q1 = h.query("g", step_s=5.0)
    assert q1["step_s"] == 5.0
    # each 5-wide fold averages its children: mean(20..24)=22, ...
    assert [p["value"] for p in q1["points"]] == [22.0, 27.0, 32.0, 37.0,
                                                  42.0, 47.0]
    q2 = h.query("g", step_s=25.0)
    assert q2["step_s"] == 25.0
    assert [p["value"] for p in q2["points"]] == [12.0, 37.0]


def test_counter_folds_sum_and_reset_never_negative():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    cum = 0.0
    for t in range(12):
        cum += 2.0
        if t == 7:
            cum = 1.0  # replica restart mid-run
        h.record(float(t), _counter(cum))
    q = h.query("c")
    deltas = [p["delta"] for p in q["points"]]
    assert all(d >= 0.0 for d in deltas)
    # tick 0 baselines at 2.0 (first scrape), tick 7 resets to 1.0
    assert deltas[-5] == 1.0  # the reset tick
    rates = [p["rate"] for p in q["points"]]
    assert rates == deltas  # step is 1 s
    # tier-1 folds are SUMS of deltas (increase over 5 s), not averages
    q1 = h.query("c", step_s=5.0)
    assert q1["points"][0]["delta"] == pytest.approx(10.0)  # ticks 0-4
    assert q1["points"][0]["rate"] == pytest.approx(2.0)


def test_histogram_windowed_quantile_matches_direct_reference():
    bounds = (0.1, 0.5, 1.0, 5.0)
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    # cumulative growth: each tick adds one observation per bucket slot
    # according to a schedule; track the flat list of per-window deltas
    cum = [0, 0, 0, 0, 0]
    schedule = [
        [1, 0, 0, 0, 0], [0, 2, 0, 0, 0], [0, 0, 3, 0, 0],
        [0, 0, 0, 1, 0], [2, 1, 0, 0, 1], [0, 0, 4, 0, 0],
    ]
    count = 0
    total = 0.0
    for t, add in enumerate(schedule):
        cum = [c + a for c, a in zip(cum, add)]
        count += sum(add)
        total += sum(add) * 0.3
        h.record(float(t), _hist(count, total, cum, bounds=bounds))
    # reference: windowed bucket deltas over the last 3 ticks = the sum
    # of the last 3 schedule rows, interpolated the same way
    ref_buckets = [sum(col) for col in zip(*schedule[3:])]
    ref = hist_quantile(bounds, ref_buckets, 0.95)
    got = h.quantile("h", 0.95, window_s=3.0, now=5.0)
    assert got == pytest.approx(ref)
    # whole-history window equals the full cumulative distribution
    # (window 6 s stays on the finest tier, which holds every tick)
    ref_all = hist_quantile(bounds, cum, 0.95)
    assert h.quantile("h", 0.95, window_s=6.0, now=5.0) == \
        pytest.approx(ref_all)
    # fraction_above agrees with the definition at a bucket edge
    frac = h.fraction_above("h", 5.0, window_s=6.0, now=5.0)
    assert frac == pytest.approx(cum[4] / sum(cum))


def test_tag_filter_and_cross_series_sum():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    snap = {
        "q": {
            "kind": "gauge", "tag_keys": ("deployment", "node"),
            "series": {("d1", "n1"): 3.0, ("d1", "n2"): 5.0,
                       ("d2", "n1"): 100.0},
        }
    }
    h.record(1.0, snap)
    allp = h.query("q")["points"]
    assert allp[0]["value"] == 108.0  # untagged query sums the cluster
    d1 = h.query("q", tags={"deployment": "d1"})["points"]
    assert d1[0]["value"] == 8.0  # subset-match sums within the subset
    d2n1 = h.query("q", tags={"deployment": "d2", "node": "n1"})["points"]
    assert d2n1[0]["value"] == 100.0
    assert h.query("q", tags={"deployment": "nope"})["points"] == []
    assert h.query("missing")["points"] == []


def test_series_cap_drops_and_counts():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=3)
    snap = {
        "m": {
            "kind": "gauge", "tag_keys": ("i",),
            "series": {(str(i),): float(i) for i in range(10)},
        }
    }
    h.record(1.0, snap)
    st = h.stats()
    assert st["series"] == 3
    assert st["dropped_series"] == 7
    assert st["ticks"] == 1


def test_windowed_value_gauge_counter_and_no_data():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    for t in range(5):
        h.record(float(t), {**_gauge(float(t * 10)), **_counter(float(t))})
    # cutoff is inclusive: ts >= now - window -> ticks 1,2,3,4
    assert h.windowed_value("g", window_s=3.0, now=4.0) == \
        pytest.approx(25.0)  # mean of 10,20,30,40
    assert h.windowed_value("g", window_s=3.0, agg="max", now=4.0) == 40.0
    # counter: total windowed delta / window (deltas of 1.0 at ticks 1-4)
    assert h.windowed_value("c", window_s=3.0, now=4.0) == \
        pytest.approx(4.0 / 3.0)
    assert h.windowed_value("g", window_s=3.0, now=100.0) is None
    assert h.windowed_value("nope", window_s=3.0) is None


def test_pick_tier_prefers_finest_covering_window():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=4)
    assert h._pick_tier(None, None) == 0
    assert h._pick_tier(8.0, None) == 0  # 10-point 1 s ring covers 8 s
    assert h._pick_tier(25.0, None) == 1  # needs the 5 s × 6 ring
    assert h._pick_tier(90.0, None) == 2
    assert h._pick_tier(None, 5.0) == 1  # explicit step wins
    assert h._pick_tier(None, 1000.0) == 2  # clamped to coarsest


def test_derived_request_gauges_land_in_history():
    h = MetricsHistory(base_step_s=1.0, tiers=TIERS, max_series=64)
    reqs = {"deployments": {"d1": {"e2e_s": {"p50": 0.1, "p95": 0.4,
                                             "p99": 0.9}}}}
    h.record(1.0, {}, request_summary=reqs)
    q = h.query("rt_request_e2e_p95_s", tags={"deployment": "d1"})
    assert q["points"][0]["value"] == pytest.approx(0.4)


# -- cluster e2e: sampler thread + state API + dashboard route ------------


def test_history_sampler_e2e_cluster():
    import ray_tpu
    from ray_tpu import state
    from ray_tpu.observability import core_metrics
    from ray_tpu.observability.history import HistorySampler
    from ray_tpu.utils.config import config

    config.set("metrics_sample_interval_s", 0.2)
    try:
        ray_tpu.init(num_cpus=2)
        try:
            # sampler thread exists under its documented name
            names = [t.name for t in threading.enumerate()]
            assert HistorySampler.THREAD_NAME in names
            # drive a counter from the driver (its registry is scraped)
            for _ in range(5):
                core_metrics.lease_requests.inc()
            deadline = time.time() + 15.0
            pts = []
            while time.time() < deadline:
                rep = state.metrics_history(
                    "rt_lease_requests_total", window_s=30.0
                )
                if rep.get("enabled") and rep.get("points"):
                    pts = rep["points"]
                    if sum(p["delta"] for p in pts) >= 5.0:
                        break
                time.sleep(0.2)
            assert pts, "sampler never recorded the driver counter"
            assert sum(p["delta"] for p in pts) >= 5.0
            assert all(p["delta"] >= 0.0 for p in pts)
            # inventory form (no name) reports sampler stats
            inv = state.metrics_history()
            assert inv["enabled"] and inv["ticks"] >= 1
            assert "rt_lease_requests_total" in inv["names"]
            # dashboard route parses query params and round-trips JSON
            from ray_tpu.core import worker as worker_mod
            from ray_tpu.dashboard import Dashboard

            addr = worker_mod.global_worker().control_address
            dash = Dashboard(addr, port=0)
            try:
                status, ctype, body = dash._route(
                    "/api/metrics/history?name=rt_lease_requests_total"
                    "&window_s=30&step_s=0.2"
                )
                assert status == 200
                rep = json.loads(body)
                assert rep["enabled"] and rep["name"] == \
                    "rt_lease_requests_total"
            finally:
                dash._server.server_close()
        finally:
            ray_tpu.shutdown()
    finally:
        config.set("metrics_sample_interval_s", 1.0)


def test_history_disabled_with_zero_interval():
    import ray_tpu
    from ray_tpu import state
    from ray_tpu.observability.history import HistorySampler
    from ray_tpu.utils.config import config

    config.set("metrics_sample_interval_s", 0)
    try:
        ray_tpu.init(num_cpus=1)
        try:
            names = [t.name for t in threading.enumerate()]
            assert HistorySampler.THREAD_NAME not in names
            assert state.metrics_history() == {"enabled": False}
            assert state.alerts() == {"enabled": False, "alerts": []}
        finally:
            ray_tpu.shutdown()
    finally:
        config.set("metrics_sample_interval_s", 1.0)
