"""Mesh/sharding/model tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jnp_mod(cpu_mesh_devices):
    import jax.numpy as jnp

    return jnp


def test_mesh_presets(cpu_mesh_devices):
    from ray_tpu.parallel import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(dp=-1, tp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    mesh2 = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    assert mesh2.shape["fsdp"] == 2
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=3, tp=2))  # 6 doesn't divide 8


def test_shard_pytree_and_constraint(cpu_mesh_devices, jnp_mod):
    import jax
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import MeshConfig, build_mesh, shard_pytree
    from ray_tpu.parallel.sharding import PartitionRules

    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    rules = PartitionRules([(r"w", P("tp", None)), (r"b", P())])
    tree = {"w": jnp_mod.ones((8, 4)), "b": jnp_mod.ones((4,))}
    sharded = shard_pytree(tree, mesh, rules)
    assert sharded["w"].sharding.spec == P("tp", None)
    np.testing.assert_array_equal(np.asarray(sharded["w"]), np.ones((8, 4)))


def test_gpt2_forward_and_loss(cpu_mesh_devices, jnp_mod):
    import jax

    from ray_tpu.models import gpt2

    cfg = gpt2.CONFIGS["gpt2-tiny"]
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = gpt2.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.padded_vocab)
    loss = gpt2.loss_fn(params, tokens, cfg)
    # random init: loss ~ log(vocab)
    assert 4.0 < float(loss) < 8.0


def test_gpt2_causality(cpu_mesh_devices, jnp_mod):
    """Changing a future token must not affect past logits."""
    import jax

    from ray_tpu.models import gpt2

    cfg = gpt2.CONFIGS["gpt2-tiny"]
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    t2 = t1.at[0, 10].set((t1[0, 10] + 1) % cfg.vocab_size)
    l1 = gpt2.forward(params, t1, cfg)
    l2 = gpt2.forward(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[0, :10]), np.asarray(l2[0, :10]), rtol=2e-2, atol=2e-2
    )
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]), atol=1e-3)


def test_gpt2_sharded_train_step_matches_single_device(cpu_mesh_devices):
    """The full dp+fsdp+tp sharded train step must match unsharded numerics."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import gpt2
    from ray_tpu.parallel import MeshConfig, build_mesh, shard_pytree
    from ray_tpu.parallel.sharding import gpt_rules, tree_shardings

    cfg = gpt2.GPT2Config(
        vocab_size=256, n_positions=64, d_model=64, n_layer=2, n_head=4,
        remat=False, dtype=jnp.float32,
    )
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(1e-3)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 256)
    step = gpt2.make_train_step(cfg, opt)

    # single device
    p1, o1, loss1 = jax.jit(step)(params, opt.init(params), tokens)

    # 8-device mesh dp=2 fsdp=2 tp=2
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    rules = gpt_rules()
    sp = shard_pytree(params, mesh, rules)
    so = shard_pytree(opt.init(params), mesh, rules)
    data_sharding = NamedSharding(mesh, P(("dcn", "dp", "fsdp")))
    stokens = jax.device_put(tokens, data_sharding)
    sharded_step = jax.jit(
        step,
        in_shardings=(
            tree_shardings(mesh, rules, params),
            tree_shardings(mesh, rules, so),
            data_sharding,
        ),
    )
    p2, o2, loss2 = sharded_step(sp, so, stokens)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat2 = jax.tree_util.tree_leaves(p2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_mlp(cpu_mesh_devices):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=16, hidden=(32,), num_classes=4)
    params = mlp.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    y = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4)
    assert mlp.forward(params, x).shape == (8, 4)
    assert float(mlp.loss_fn(params, (x, y))) > 0


def test_fused_ce_matches_reference():
    """loss_impl="fused" (custom-vjp CE head, PROFILE.md) must match the
    unchunked reference loss and gradients."""
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.models import gpt2

    cfg = gpt2.CONFIGS["gpt2-tiny"]
    cfg_fused = dataclasses.replace(cfg, loss_impl="fused", loss_chunk=16)
    cfg_ref = dataclasses.replace(cfg, loss_chunk=0)
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (2, 65), 0, cfg.vocab_size, dtype="int32"
    )
    lf = float(gpt2.loss_fn(params, toks, cfg_fused))
    lr = float(gpt2.loss_fn(params, toks, cfg_ref))
    assert abs(lf - lr) < 1e-3
    gf = jax.grad(gpt2.loss_fn)(params, toks, cfg_fused)
    gr = jax.grad(gpt2.loss_fn)(params, toks, cfg_ref)
    errs = jax.tree.map(
        lambda a, b: float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-9)),
        gf, gr,
    )
    assert max(jax.tree.leaves(errs)) < 0.05


def test_scan_unroll_same_numerics():
    import dataclasses

    import jax
    import numpy as np

    from ray_tpu.models import gpt2

    cfg = gpt2.CONFIGS["gpt2-tiny"]
    cfg_u = dataclasses.replace(cfg, scan_unroll=2)
    params = gpt2.init(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (2, 33), 0, cfg.vocab_size, dtype="int32"
    )
    # unrolling changes XLA fusion order, so bf16 logits differ in the
    # low bits; the loss must agree to bf16-roundoff tolerance
    lr = float(gpt2.loss_fn(params, toks, cfg))
    lu = float(gpt2.loss_fn(params, toks, cfg_u))
    np.testing.assert_allclose(lu, lr, rtol=2e-3)
